"""Speculative decoding engine mode + step-loop constants (ISSUE 16).

The contract under test: with a draft model attached (``spec_k > 0``)
the engine emits GREEDY streams bit-identical (np.array_equal, no
tolerance) to the non-speculative path — the verify forward makes
acceptance provable, so draft quality only moves THROUGHPUT, never
tokens. Covered here: spec-vs-plain exactness across page/bucket
boundaries, perfect-draft step compression, mesh-sharded spec replicas,
rejection-rollback page accounting under a randomized soak with
cancels/deadlines mid-round, composition with prefix-cache and chunked
prefill, the draftless/mixed-temperature fallbacks with draft resync,
the fused device sampler's greedy parity, warmup pre-dispatch, and the
jaxlib 0.4.37 donated-executable fresh-compile guard. All CPU, tiny
configs — tier-1 safe."""

import numpy as np
import pytest


def _tiny(max_seq_len=1024):
    import jax

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, mlp_dim=64,
                            max_seq_len=max_seq_len)
    params = llama.init_params(cfg, jax.random.key(0))
    return cfg, params


def _tiny_draft(cfg):
    """A genuinely smaller draft over the SAME vocab: proposals are
    frequently wrong, so acceptance, rejection and rollback all
    exercise for real."""
    import jax

    from ray_tpu.models import llama

    dcfg = llama.LlamaConfig(vocab_size=cfg.vocab_size, dim=16,
                             n_layers=1, n_heads=2, n_kv_heads=1,
                             mlp_dim=32, max_seq_len=cfg.max_seq_len)
    return dcfg, llama.init_params(dcfg, jax.random.key(1))


def _drive(eng, reqs, budget=600):
    for _ in range(budget):
        if all(r.done.is_set() for r in reqs):
            return
        eng.step()
    raise AssertionError(
        f"requests not done in {budget} steps: "
        f"{[r.status for r in reqs]}")


def _outputs(eng, prompts, n_tok, **submit_kw):
    reqs = [eng.submit(p, max_new_tokens=n_tok, **submit_kw)
            for p in prompts]
    _drive(eng, reqs)
    return [np.asarray(r.output, np.int32) for r in reqs]


@pytest.fixture(scope="module")
def model():
    return _tiny()


@pytest.fixture(scope="module")
def draft(model):
    return _tiny_draft(model[0])


def _spec_engine(model, draft, k=4, **kw):
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = model
    dcfg, dparams = draft
    kw.setdefault("page_tokens", 16)
    kw.setdefault("capacity", 256)
    return DecodeEngine(params, cfg, slots=4,
                        spec_draft_params=dparams,
                        spec_draft_config=dcfg, spec_k=k, **kw)


def _plain_engine(model, **kw):
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = model
    kw.setdefault("page_tokens", 16)
    kw.setdefault("capacity", 256)
    return DecodeEngine(params, cfg, slots=4, **kw)


# ----------------------------------------------------- greedy exactness


def test_spec_greedy_bit_exact_across_boundaries(model, draft):
    """Spec output == plain output, np.array_equal, with prompts and
    generation lengths chosen to cross page (16) and suffix-bucket
    boundaries mid-round: 15+18 straddles a page edge inside one
    accepted run, 30+24 crosses two."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 60, size=n).tolist()
               for n in (5, 15, 17, 30)]
    plain = _plain_engine(model)
    want = _outputs(plain, prompts, 24)
    plain.shutdown()
    spec = _spec_engine(model, draft, k=4)
    got = _outputs(spec, prompts, 24)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    s = spec.stats()["spec"]
    assert s["rounds"] > 0 and s["proposed_tokens"] > 0
    # every step either emitted or fell back — never lost a token
    assert spec.tokens_out == sum(len(w) for w in want)
    spec.shutdown()


@pytest.mark.slow  # PR 20 rebudget (7.8s): step-compression property;
# accept-rate plumbing and bit-exactness keep their own tier-1 gates
def test_spec_perfect_draft_compresses_steps(model):
    """Draft == target => every proposal accepted (rate 1.0) and the
    target runs ~1/(k+1) as many forwards: the acceptance math, length
    bookkeeping and multi-token emission all land in one assert."""
    cfg, params = model
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 60, size=n).tolist()
               for n in (6, 13, 21, 34)]
    plain = _plain_engine(model)
    want = _outputs(plain, prompts, 24)
    base_steps = plain.steps
    plain.shutdown()
    spec = _spec_engine(model, (cfg, params), k=4)
    got = _outputs(spec, prompts, 24)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    s = spec.stats()["spec"]
    assert s["accept_rate"] == 1.0
    assert spec.steps * 3 < base_steps
    spec.shutdown()


@pytest.mark.slow  # PR 20 rebudget (6.9s): truncation edge case;
# boundary bit-exactness stays tier-1
def test_spec_eos_and_max_tokens_truncate_mid_round(model):
    """EOS landing inside an accepted run must cut the stream exactly
    where sequential decode would: drive plain first to learn a token
    that appears mid-stream, then replay both engines with it as
    eos_id."""
    cfg, params = model
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 60, size=n).tolist() for n in (8, 19)]
    probe = _plain_engine(model)
    ref = _outputs(probe, prompts, 20)
    probe.shutdown()
    eos = int(ref[0][4])  # 5th token of stream 0 = a mid-round EOS
    plain = _plain_engine(model)
    want = _outputs(plain, prompts, 20, eos_id=eos)
    plain.shutdown()
    spec = _spec_engine(model, (cfg, params), k=4)  # perfect draft:
    #   the accepted run is guaranteed to span the EOS position
    got = _outputs(spec, prompts, 20, eos_id=eos)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    assert got[0][-1] == eos and len(got[0]) <= 5
    spec.shutdown()


MESHES = [
    # One shape stays in tier-1 (the full-model-axis one); the other
    # two re-trace the same programs under different divisibility
    # splits and ride the slow lane (tier-1 budget).
    pytest.param((1, 8), marks=pytest.mark.slow),   # 8.6s: re-trace only
    pytest.param((2, 4), marks=pytest.mark.slow),   # 4.9s: re-trace only
    (8, 1),
]


@pytest.mark.parametrize("mesh_shape", MESHES)
def test_spec_mesh_sharded_bit_exact(mesh_shape):
    """Spec mode on a GSPMD decode mesh == single-chip plain decode,
    np.array_equal: the verify/draft programs trace under the decode
    axis rules (draft under its OWN divisibility specialization), so
    sharding moves bytes, never logits."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.decode import DecodeEngine

    cfg = llama.LlamaConfig(vocab_size=64, dim=32, n_layers=2,
                            n_heads=8, n_kv_heads=8, mlp_dim=64,
                            max_seq_len=256)
    params = llama.init_params(cfg, jax.random.key(0))
    dcfg = llama.LlamaConfig(vocab_size=64, dim=16, n_layers=1,
                             n_heads=8, n_kv_heads=8, mlp_dim=32,
                             max_seq_len=256)
    dparams = llama.init_params(dcfg, jax.random.key(1))
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 60, size=n).tolist() for n in (7, 18)]
    plain = DecodeEngine(params, cfg, slots=8, capacity=128,
                         page_tokens=16)
    want = _outputs(plain, prompts, 16)
    plain.shutdown()
    spec = DecodeEngine(params, cfg, slots=8, capacity=128,
                        page_tokens=16, mesh_shape=mesh_shape,
                        spec_draft_params=dparams,
                        spec_draft_config=dcfg, spec_k=3)
    got = _outputs(spec, prompts, 16)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    assert spec.stats()["spec"]["rounds"] > 0
    spec.shutdown()


# ------------------------------------------------- rollback accounting


def test_spec_rollback_soak_zero_leaked_pages(model, draft):
    """200+ randomized steps against a REAL (wrong-often) draft:
    admissions, cancels and deadlines land mid-round, rejected tails
    roll page cursors back every few rounds, the overcommitted pools
    preempt. Terminal invariants: both allocators drain to exactly the
    prefix pins (target) and zero (draft), and un-shared completions
    are token-exact vs plain."""
    cfg, params = model
    dcfg, dparams = draft
    from ray_tpu.serve.decode import DecodeEngine

    rng = np.random.default_rng(42)
    eng = DecodeEngine(params, cfg, slots=4, capacity=256,
                       page_tokens=16, pool_pages=48,
                       spec_draft_params=dparams, spec_draft_config=dcfg,
                       spec_k=4, spec_draft_pool_pages=40,
                       prefix_pool_entries=4, prefix_match_min_tokens=16)
    plain = _plain_engine(model)
    live, done, submitted = [], [], 0
    for _ in range(240):
        if submitted < 20 and rng.random() < 0.3 and len(live) < 8:
            prompt = rng.integers(
                1, 60, size=int(rng.integers(3, 60))).tolist()
            n = int(rng.integers(1, 28))
            dl = (0.02 if rng.random() < 0.08 else None)  # expires
            #   mid-flight, usually inside a spec round
            live.append([eng.submit(prompt, max_new_tokens=n,
                                    deadline_s=dl), prompt, n, False])
            submitted += 1
        if live and rng.random() < 0.06:
            victim = live[int(rng.integers(len(live)))]
            if not victim[3]:
                eng.cancel(victim[0].request_id)
                victim[3] = True
        eng.step()
        for e in list(live):
            if e[0].done.is_set():
                live.remove(e)
                done.append(e)
    for _ in range(2000):
        if all(e[0].done.is_set() for e in live):
            break
        eng.step()
    done += live
    assert all(e[0].done.is_set() for e in done)
    exact = 0
    for req, prompt, n, cancelled in done:
        if req.status != "completed":
            continue
        if req.prompt_len == len(prompt) and req.prefix_len == 0:
            [want] = _outputs(plain, [prompt], n)
            assert np.array_equal(want,
                                  np.asarray(req.output, np.int32))
            exact += 1
    assert exact >= 5
    s = eng.stats()
    assert s["pages_in_use"] == s["pages_pinned"], "leaked target pages"
    assert s["spec"]["draft_pages_free"] \
        == s["spec"]["draft_pages_total"], "leaked draft pages"
    assert s["spec"]["rounds"] > 20
    assert 0 < s["spec"]["accepted_tokens"] \
        < s["spec"]["proposed_tokens"], \
        "soak must see both acceptance and rejection"
    plain.shutdown()
    eng.shutdown()


# ----------------------------------------------------------- composition


@pytest.mark.slow  # PR 20 rebudget (6.2s): composition variant;
# spec and prefix cache each keep their own tier-1 gates
def test_spec_composes_with_prefix_cache(model, draft):
    """Second submission of a shared prompt splices cached pages into
    the TARGET while the draft re-prefills (it has no prefix index) —
    outputs stay bit-exact and the hit really happened."""
    rng = np.random.default_rng(9)
    shared = rng.integers(1, 60, size=48).tolist()
    prompts = [shared + [7], shared + [11]]
    plain = _plain_engine(model, prefix_pool_entries=4,
                          prefix_match_min_tokens=16)
    want = [_outputs(plain, [p], 16)[0] for p in prompts]
    plain.shutdown()
    spec = _spec_engine(model, draft, k=3, prefix_pool_entries=4,
                        prefix_match_min_tokens=16)
    got = [_outputs(spec, [p], 16)[0] for p in prompts]
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    assert spec.stats()["prefix"]["hits"] >= 1
    spec.shutdown()


@pytest.mark.slow  # PR 20 rebudget (10.9s): composition variant;
# chunked prefill and spec each keep their own tier-1 bit-exact gates
def test_spec_composes_with_chunked_prefill(model, draft):
    """A long prompt admits through chunked prefill WHILE a short one
    decodes speculatively: spec rounds run with a mid-prefill slot in
    the batch (its verify row is junk routed to scratch/overwritten
    positions) and both streams match the plain chunked engine."""
    rng = np.random.default_rng(13)
    long_p = rng.integers(1, 60, size=150).tolist()
    short_p = rng.integers(1, 60, size=6).tolist()

    def run(eng):
        r_short = eng.submit(short_p, max_new_tokens=24)
        r_long = eng.submit(long_p, max_new_tokens=12)
        _drive(eng, [r_short, r_long])
        return (np.asarray(r_short.output, np.int32),
                np.asarray(r_long.output, np.int32))

    plain = _plain_engine(model, prefill_chunk_tokens=32, capacity=512)
    want = run(plain)
    plain.shutdown()
    spec = _spec_engine(model, draft, k=4, prefill_chunk_tokens=32,
                        capacity=512)
    got = run(spec)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    assert spec.prefill_chunks > 0, "chunked path really ran"
    assert spec.stats()["spec"]["rounds"] > 0
    spec.shutdown()


def test_spec_mixed_temperature_falls_back_and_resyncs(model, draft):
    """A sampled request in the batch parks spec on the plain path (the
    acceptance rule is argmax-only); when it finishes, spec resumes on
    slots whose drafts fell arbitrarily behind — the resync prefill
    rebuilds them and the greedy stream stays bit-exact end to end."""
    cfg, params = model
    rng = np.random.default_rng(17)
    greedy_p = rng.integers(1, 60, size=9).tolist()
    sampled_p = rng.integers(1, 60, size=5).tolist()
    plain = _plain_engine(model)
    [want] = _outputs(plain, [greedy_p], 40)
    plain.shutdown()
    spec = _spec_engine(model, draft, k=3)
    r_g = spec.submit(greedy_p, max_new_tokens=40)
    r_s = spec.submit(sampled_p, max_new_tokens=6, temperature=0.9)
    _drive(spec, [r_g, r_s])
    assert np.array_equal(want, np.asarray(r_g.output, np.int32))
    assert spec.stats()["spec"]["rounds"] > 0, \
        "spec must resume after the sampled request drains"
    spec.shutdown()


def test_spec_draftless_fallback_stays_exact(model, draft):
    """A draft pool too small to seat anything demotes slots to
    draftless (junk proposals, all rejected): output identical, zero
    acceptance bookkeeping, no leak."""
    rng = np.random.default_rng(19)
    prompts = [rng.integers(1, 60, size=n).tolist() for n in (40, 50)]
    plain = _plain_engine(model)
    want = _outputs(plain, prompts, 12)
    plain.shutdown()
    spec = _spec_engine(model, draft, k=3, spec_draft_pool_pages=2)
    got = _outputs(spec, prompts, 12)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    s = spec.stats()["spec"]
    assert s["proposed_tokens"] == 0, \
        "draftless slots must not pollute acceptance metrics"
    assert s["draft_pages_free"] == s["draft_pages_total"]
    spec.shutdown()


def test_spec_requires_paged_kv(model, draft):
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = model
    dcfg, dparams = draft
    with pytest.raises(ValueError, match="paged"):
        DecodeEngine(params, cfg, slots=2, capacity=128, page_tokens=0,
                     spec_draft_params=dparams, spec_draft_config=dcfg,
                     spec_k=4)


# -------------------------------------------------- device-side sampler


@pytest.mark.parametrize("page_tokens", [16, 0])
def test_device_sampler_greedy_parity(model, page_tokens):
    """Fused device sampling returns the SAME greedy streams as the
    host sampler (argmax with first-max tiebreak on both sides), paged
    and contiguous."""
    rng = np.random.default_rng(23)
    prompts = [rng.integers(1, 60, size=n).tolist() for n in (4, 12, 27)]
    host = _plain_engine(model, page_tokens=page_tokens)
    want = _outputs(host, prompts, 18)
    host.shutdown()
    dev = _plain_engine(model, page_tokens=page_tokens,
                        device_sampler=True)
    got = _outputs(dev, prompts, 18)
    for w, g in zip(want, got):
        assert np.array_equal(w, g)
    dev.shutdown()


def test_device_sampler_sampled_rows_deterministic(model):
    """Sampled rows move to the program's counter-based RNG stream:
    still deterministic (two identical engines agree token-for-token),
    just not the host numpy stream."""
    rng = np.random.default_rng(29)
    prompts = [rng.integers(1, 60, size=8).tolist()]
    outs = []
    for _ in range(2):
        eng = _plain_engine(model, device_sampler=True)
        outs.append(_outputs(eng, prompts, 12,
                             temperature=0.8)[0])
        eng.shutdown()
    assert np.array_equal(outs[0], outs[1])
    assert all(0 <= t < _tiny()[0].vocab_size for t in outs[0])


@pytest.mark.slow  # PR 20 rebudget (8.2s): warmup perf property
def test_warmup_predispatches_step_programs(model, draft):
    """warmup() compiles the step-loop grid before traffic: the compile
    keys are marked, the parked KV lengths come back zeroed, and the
    first real requests emit the exact greedy streams."""
    import numpy as _np

    spec = _spec_engine(model, draft, k=3, decode_chunk=4,
                        device_sampler=True)
    spec.warmup()
    for key in [("decode",), ("decode_k", 2), ("decode_k", 4),
                ("decode_sampled",), ("spec_draft", 3),
                ("spec_verify", 3), ("paged_prefill", 1, 128)]:
        assert key in spec._compiled, key
    assert _np.asarray(spec.cache["length"]).sum() == 0
    assert _np.asarray(spec._draft_cache["length"]).sum() == 0
    rng = np.random.default_rng(31)
    prompts = [rng.integers(1, 60, size=10).tolist()]
    plain = _plain_engine(model)
    want = _outputs(plain, prompts, 10)
    plain.shutdown()
    got = _outputs(spec, prompts, 10)
    assert np.array_equal(want[0], got[0])
    spec.shutdown()


# ------------------------------------- donated-executable compile guard


def test_no_persistent_cache_guard_scopes_and_restores():
    """The jaxlib 0.4.37 pin (PR 14): donated executables reloaded from
    the persistent XLA compile cache are corrupt. _dispatch_fresh must
    detach the disk cache for exactly the FIRST dispatch of a donated
    program and restore it after — including on error."""
    import jax

    from ray_tpu.serve.decode import _no_persistent_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/_specpc")
        with _no_persistent_cache(jax):
            assert jax.config.jax_compilation_cache_dir is None
        assert jax.config.jax_compilation_cache_dir == "/tmp/_specpc"
        with pytest.raises(RuntimeError):
            with _no_persistent_cache(jax):
                assert jax.config.jax_compilation_cache_dir is None
                raise RuntimeError("boom")
        assert jax.config.jax_compilation_cache_dir == "/tmp/_specpc"
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_dispatch_fresh_detaches_only_first_dispatch(model, draft):
    import jax

    spec = _spec_engine(model, draft, k=2)
    prev = jax.config.jax_compilation_cache_dir
    seen = []
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/_specpc")
        spec._dispatch_fresh(
            ("probe",),
            lambda: seen.append(jax.config.jax_compilation_cache_dir))
        spec._dispatch_fresh(
            ("probe",),
            lambda: seen.append(jax.config.jax_compilation_cache_dir))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    assert seen == [None, "/tmp/_specpc"]
    assert ("probe",) in spec._compiled
    spec.shutdown()


# --------------------------------------------------------- observability


@pytest.mark.slow  # PR 20 rebudget (6.2s): stats/steplog plumbing;
# spec correctness and accept-rate math keep their fast gates
def test_spec_stats_steplog_and_deployment_plumbing(model, draft):
    """spec stats() block, draft/verify steplog phases, timeline() spec
    flag, and the deployment-level replica_metrics passthrough."""
    from ray_tpu.serve.decode import LlamaDecodeDeployment

    spec = _spec_engine(model, draft, k=3, step_timeline=128)
    rng = np.random.default_rng(37)
    _outputs(spec, [rng.integers(1, 60, size=9).tolist()], 12)
    s = spec.stats()["spec"]
    for key in ("k", "rounds", "proposed_tokens", "accepted_tokens",
                "accept_rate", "draft_pages_total", "draft_pages_free"):
        assert key in s
    assert s["k"] == 3 and s["rounds"] > 0
    tl = spec.timeline()
    assert tl["spec_k"] == 3
    names = [p["phase"] for row in tl["rows"] for p in row["phases"]]
    assert "draft" in names and "verify" in names
    vp = [p for row in tl["rows"] for p in row["phases"]
          if p["phase"] == "verify"]
    assert all("accepted" in p and p["k"] == 3 for p in vp)
    spec.shutdown()

    dep = LlamaDecodeDeployment.__new__(LlamaDecodeDeployment)
    dep.engine = _spec_engine(model, draft, k=3)
    _outputs(dep.engine, [rng.integers(1, 60, size=7).tolist()], 8)
    rm = dep.replica_metrics()
    assert rm["spec"]["rounds"] > 0
    dep.engine.shutdown()


def test_spec_terminal_metrics_observed(model, draft):
    """Per-request spec counters/histogram land at the terminal step
    through serve.metrics and aggregate into slo_summary."""
    import uuid

    from ray_tpu.serve import metrics as smetrics
    from ray_tpu.util.metrics import _Registry

    dep = f"specdep-{uuid.uuid4().hex[:6]}"
    spec = _spec_engine(model, draft, k=3, metrics_enabled=True,
                        metrics_deployment=dep)
    rng = np.random.default_rng(41)
    _outputs(spec, [rng.integers(1, 60, size=11).tolist()], 12)
    spec.shutdown()
    summary = smetrics.slo_summary(
        {"local": _Registry.get().snapshot()})
    rec = summary.get(dep, {})
    assert rec.get("spec_proposed_tokens", 0) > 0
    assert 0 <= rec.get("spec_accepted_tokens", 0) \
        <= rec["spec_proposed_tokens"]
    assert rec["spec_accept_rate"]["count"] >= 1


def test_spec_off_path_unchanged(model):
    """spec OFF = byte-identical legacy behavior: no draft structures,
    no spec stats key, plain step loop."""
    eng = _plain_engine(model)
    assert eng.spec is False
    assert "spec" not in eng.stats()
    assert not hasattr(eng, "_draft_pages") or not eng.spec
    rng = np.random.default_rng(43)
    _outputs(eng, [rng.integers(1, 60, size=6).tolist()], 6)
    assert "spec" not in eng.stats()
    eng.shutdown()
