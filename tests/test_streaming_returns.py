"""Streaming-generator return tests (reference: num_returns="streaming" /
ObjectRefGenerator, core worker streaming returns)."""

import time

import numpy as np
import pytest

import ray_tpu


def test_generator_streams_items_before_completion(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    values = [ray_tpu.get(ref) for ref in g]
    assert values == [0, 10, 20, 30, 40]


def test_generator_items_arrive_incrementally(ray_start_regular):
    """The first item must be consumable while the task is still running."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(3):
            yield i
            time.sleep(1.0)

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(g.next_ready(timeout=30))
    elapsed = time.monotonic() - t0
    assert first == 0
    # Arrived well before the ~3s total runtime of the task.
    assert elapsed < 2.0, elapsed
    rest = [ray_tpu.get(ref) for ref in g]
    assert rest == [1, 2]


def test_generator_large_items_via_store(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full((256, 1024), i, np.float32)  # 1 MB each

    total = 0.0
    for ref in big_gen.remote():
        total += float(ray_tpu.get(ref).mean())
    assert total == 0.0 + 1.0 + 2.0


def test_generator_error_propagates(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad_gen():
        yield 1
        raise ValueError("stream-boom")

    g = bad_gen.remote()
    assert ray_tpu.get(next(g)) == 1
    with pytest.raises(Exception, match="stream-boom"):
        for ref in g:
            ray_tpu.get(ref)


def test_empty_generator(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return
        yield  # pragma: no cover

    assert list(empty.remote()) == []
