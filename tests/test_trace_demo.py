"""`make trace-demo` gate (tier-1, fast): a tiny serve session through
the real HTTP proxy emits a Chrome trace that loads as JSON and is
causally linked — spans from >=3 processes (client driver, proxy with
its router, replica engines) with >=1 cross-process parent/child span
pair, plus engine step-timeline slices merged into the same trace.
This is the ISSUE 9 acceptance path run in-process against the test
fixture cluster (the Makefile target runs the same function
standalone)."""

import json
import os

import pytest


@pytest.mark.slow  # 11s: end-to-end trace demo; span/link coverage
# stays via test_serve_observability (PR 16 rebudget)
def test_trace_demo_emits_causally_linked_trace(ray_start_regular,
                                                tmp_path):
    from ray_tpu.serve.trace_demo import run_demo

    out = os.path.join(str(tmp_path), "serve_trace.json")
    report = run_demo(output=out, init=False, replicas=2, requests=3)

    # run_demo already raised on any validation failure; pin the
    # acceptance specifics here too so a weakened validator can't
    # silently pass.
    assert report["spans"] >= 5
    assert len(report["span_pids"]) >= 3, report["span_pids"]
    assert report["cross_process_links"], report
    assert report["engine_slices"] >= 1
    with open(out) as f:
        trace = json.load(f)
    names = {t["name"] for t in trace if t.get("cat") == "span"}
    # The request-path span vocabulary is present end to end.
    assert any(n.startswith("http:/trace_demo") for n in names), names
    assert any(n.startswith("router:") for n in names), names
    assert "attempt" in names
    assert {"queue-wait", "decode", "engine-request"} <= names, names
    # Cross-process causality includes the proxy->replica hop.
    assert any(child.startswith("actor:")
               or parent.startswith("attempt")
               for parent, child in report["cross_process_links"]), report
