"""graftlint v4 tests: the epoch-fence protocol checker (family #12)
and donated-buffer aliasing safety (family #13).

Same layering as tests/test_analysis{,_v2,_v3}.py:

1. Per-rule TP/TN fixtures — synthetic modules fed straight to the
   checkers (no jax, no cluster), including the fence-carrier
   transitive propagation and the same-line-rebind donation idiom.
2. Mutation fixtures on the REAL repo sources: reverting each of this
   PR's true-positive fixes (the multihost reservation-write verdict
   check, the serve-controller fenced save, the snapshot epoch key,
   a decode _dispatch_fresh wrap, a decode np.array copy) or flipping
   a protocol comparison is caught statically, by finding name — the
   acceptance criterion that ``make lint`` fails on any revert.
   donation-read-after-donate has no repo occurrence by design (every
   donated dispatch rebinds its result), so it is synthetic-only.
3. Collector-liveness guards: the site/index collectors still see the
   real repo's fenced writes and donated programs (an idiom drift that
   silently empties a collector would otherwise read as "clean").
4. Per-family repo-clean gates + --diff (emit_files) slice coverage.

Budget note: the module shares ONE parsed base project and ONE repo
call graph across all repo-level tests; each mutation fixture re-parses
only the mutated file and rebuilds just the graph (~1.5 s apiece).
"""

import functools
import textwrap

import pytest

from ray_tpu.analysis import repo_root, rules, run_analysis
from ray_tpu.analysis import donation_safety, fence_safety
from ray_tpu.analysis.callgraph import CallGraph
from ray_tpu.analysis.core import Project, SourceFile

FENCE_RULES = set(rules.FAMILIES["fence-safety"])
DONATION_RULES = set(rules.FAMILIES["donation-aliasing"])


def project_at(modules) -> Project:
    """Synthetic project keyed by repo-relative subpath (so fixtures
    can land on the paths the rules tables point at)."""
    files = []
    for sub, src in modules.items():
        rel = f"ray_tpu/{sub}.py"
        files.append(SourceFile(f"/fixture/{rel}", rel,
                                textwrap.dedent(src)))
    return Project("/fixture", files)


def run_checker(check, project):
    graph = CallGraph(project)
    findings = check(graph)
    by_rel = {f.relpath: f for f in project.files}
    return [f for f in findings
            if not by_rel[f.path].suppressed(f.rule, f.line)]


@functools.lru_cache(maxsize=1)
def _base_project() -> Project:
    return Project.load(repo_root())


@functools.lru_cache(maxsize=1)
def _repo_graph() -> CallGraph:
    graph = CallGraph(_base_project())
    graph.edges()
    return graph


def repo_mutant(path, old, new) -> Project:
    """The real repo with ONE file's text patched (nothing touches
    disk; unmutated files reuse the shared parsed base project)."""
    base = _base_project()
    files = []
    hit = False
    for f in base.files:
        if f.relpath == path:
            text = f.text.replace(old, new)
            assert text != f.text, f"mutation no-op in {path}: {old!r}"
            files.append(SourceFile(f.abspath, f.relpath, text))
            hit = True
        else:
            files.append(f)
    assert hit, path
    return Project(base.root, files)


def _pragma_filtered(findings, project):
    by_rel = {f.relpath: f for f in project.files}
    return [f for f in findings
            if not (f.path in by_rel
                    and by_rel[f.path].suppressed(f.rule, f.line))]


def mutant_findings(check, path, old, new):
    project = repo_mutant(path, old, new)
    graph = CallGraph(project)
    return _pragma_filtered(check(graph), project), graph


# ===================================================== fence-safety
# ------------------------------------- fence-result-ignored (TP/TN)


def test_fence_result_ignored_tp_tn():
    project = project_at({"fix/gangs": """
        class Gang:
            def bad(self, stub, epoch):
                stub.mh_group_put("g", "k", "v", epoch)

            def bad_assign(self, stub):
                put = stub.kv_put_fenced("k", b"v", 1, "e")

            def good(self, stub, epoch):
                res = stub.mh_group_put("g", "k", "v", epoch)
                if not (res or {}).get("ok"):
                    raise RuntimeError("deposed")
    """})
    found = run_checker(fence_safety.check, project)
    assert {f.rule for f in found} == {rules.FENCE_RESULT_IGNORED}
    assert {f.symbol for f in found} == {"Gang.bad", "Gang.bad_assign"}


def test_fence_carrier_chain_charges_the_discarding_caller():
    """A function that just forwards the verdict (bare return) is a
    fence CARRIER: the finding lands at ITS call sites, transitively,
    and a consuming caller stays clean."""
    project = project_at({"fix/carrier": """
        class Gang:
            def _put(self, stub):
                return stub.kv_put_fenced("k", b"v", 1, "e")

            def bad(self, stub):
                self._put(stub)

            def good(self, stub):
                out = self._put(stub)
                return bool(out)
    """})
    found = run_checker(fence_safety.check, project)
    assert len(found) == 1
    f = found[0]
    assert f.rule == rules.FENCE_RESULT_IGNORED
    assert f.symbol == "Gang.bad"
    assert "fence carrier" in f.message and "Gang._put" in f.message


def test_fenced_rpc_string_form_is_covered():
    project = project_at({"fix/stringform": """
        class Gang:
            def bad(self, client):
                client.call("kv_put_fenced", "k", b"v", 1, "e")

            def good(self, client):
                ok = client.call("kv_put_fenced", "k", b"v", 1, "e")
                return {"ok": bool(ok)}
    """})
    found = run_checker(fence_safety.check, project)
    assert [f.symbol for f in found] == ["Gang.bad"]


# ---------------------------- unfenced-mutation-in-fenced-class


def test_unfenced_mutation_tp_tn():
    project = project_at({"fix/fenced_cls": """
        class ServeController:
            def bad_raw(self, stub):
                ok = stub.kv_put("k", b"v")
                return ok

            def bad_string(self, client):
                out = client.call("kv_put", "k", b"v")
                return out

            def bad_epochless_publish(self, stub, snap, v):
                r = stub.psub_publish("ch", "key", snap, v)
                return r

        class Bystander:
            def fine(self, stub):
                ok = stub.kv_put("k", b"v")
                return ok
    """})
    found = run_checker(fence_safety.check, project)
    assert {f.rule for f in found} == {rules.FENCE_UNFENCED_MUTATION}
    assert {f.symbol for f in found} == {
        "ServeController.bad_raw", "ServeController.bad_string",
        "ServeController.bad_epochless_publish"}


# ----------------------------------- epoch-compare-direction


def test_compare_direction_equal_ok_tp_tn_and_mirror():
    """equal-ok clocks reject only STRICTLY older; <= drops a
    legitimate same-epoch republish. The mirrored spelling (stored on
    the left) normalizes to the same verdict; constant comparands are
    sentinel checks, not protocol."""
    project = project_at({"core/multihost": """
        class Registry:
            def bad(self, epoch, rec):
                if epoch <= rec.epoch:
                    return {"ok": False, "reason": "stale_epoch"}
                return {"ok": True}

            def bad_mirrored(self, epoch, rec):
                if rec.epoch >= epoch:
                    return {"ok": False}
                return {"ok": True}

            def good(self, epoch, rec):
                if epoch < rec.epoch:
                    return {"ok": False, "reason": "stale_epoch"}
                return {"ok": True}

            def sentinel(self, rec):
                return rec.epoch > 0
    """})
    found = run_checker(fence_safety.check, project)
    assert {f.rule for f in found} == {rules.FENCE_COMPARE_DIRECTION}
    assert {f.symbol for f in found} == {"Registry.bad",
                                         "Registry.bad_mirrored"}
    assert all("equal must be ACCEPTED" in f.message for f in found)


def test_compare_direction_strict_tp_tn():
    """strict clocks (weight versions) must reject EQUAL: < lets a
    replayed version re-apply."""
    project = project_at({"rl/distributed/fanout": """
        class WeightFanout:
            def bad(self, version):
                if version < self._version:
                    raise ValueError("stale")
                self._version = version

            def good(self, version):
                if version <= self._version:
                    raise ValueError("stale or replayed")
                self._version = version
    """})
    found = run_checker(fence_safety.check, project)
    assert [f.symbol for f in found] == ["WeightFanout.bad"]
    assert "equal must be REJECTED" in found[0].message


# ----------------------------------------- epoch-not-threaded


def test_epoch_not_threaded_tp_tn():
    project = project_at({"fix/snapshots": """
        class ServeController:
            def bad(self, stub, v):
                snap = {"replicas": []}
                r = stub.psub_publish("ch", "k", snap, v, self._epoch)
                return r

            def good(self, stub, v):
                snap = {"epoch": self._epoch, "replicas": []}
                r = stub.psub_publish("ch", "k", snap, v, self._epoch)
                return r

            def opaque(self, stub, v, snap):
                # non-literal payloads are not evidence either way
                r = stub.psub_publish("ch", "k", snap, v, self._epoch)
                return r
    """})
    found = run_checker(fence_safety.check, project)
    assert [(f.rule, f.symbol) for f in found] == [
        (rules.FENCE_EPOCH_NOT_THREADED, "ServeController.bad")]


# ================================================= donation-aliasing


DONATED_ENGINE = """
    import numpy as np
    import jax.numpy as jnp
    from jax import jit

    def step_fn(params, cache, toks):
        return toks, cache

    class Eng:
        def __init__(self):
            self._decode = jit(step_fn, donate_argnums=(1,))
            self._compiled = set()

        def _dispatch_fresh(self, key, call):
            self._compiled.add(key)
            return call()

        def bad(self, toks):
            logits, self.cache = self._decode(self.params, self.cache,
                                              toks)
            return logits

        def good(self, toks):
            logits, self.cache = self._dispatch_fresh(
                ("decode",),
                lambda: self._decode(self.params, self.cache, toks))
            return logits
"""


def test_donation_unguarded_dispatch_tp_tn():
    project = project_at({"fix/engine": DONATED_ENGINE})
    found = run_checker(donation_safety.check, project)
    assert [(f.rule, f.symbol) for f in found] == [
        (rules.DONATION_UNGUARDED, "Eng.bad")]
    assert "_dispatch_fresh" in found[0].message


def test_donation_asarray_alias_tp_tn():
    """np.asarray over a dispatch-result local or donated device state
    is a host VIEW the next donated dispatch clobbers; np.array (copy)
    and device-side jnp.asarray are both fine."""
    project = project_at({"fix/engine2": DONATED_ENGINE + """
        def alias_local(self):
            out, self.cache = self._dispatch_fresh(
                ("d",),
                lambda: self._decode(self.params, self.cache, 0))
            return np.asarray(out)

        def alias_attr(self):
            return np.asarray(self.cache["k"])

        def copies(self):
            out, self.cache = self._dispatch_fresh(
                ("d",),
                lambda: self._decode(self.params, self.cache, 0))
            host = np.array(out)
            dev = jnp.asarray(out)
            return host, dev
    """})
    found = [f for f in run_checker(donation_safety.check, project)
             if f.rule == rules.DONATION_ASARRAY_ALIAS]
    assert {f.symbol for f in found} == {"Eng.alias_local",
                                         "Eng.alias_attr"}


def test_donation_read_after_donate_tp_tn():
    """No repo occurrence by design (every donated dispatch rebinds its
    result), so the rule is pinned synthetically: a local read again
    after riding a donated argument position fires; the same-line
    rebind ``x, c = f(c)`` is the safe idiom and stays clean."""
    project = project_at({"fix/engine3": DONATED_ENGINE + """
        def bad_read(self, cache, toks):
            logits, fresh = self._decode(self.params, cache, toks)
            return logits, cache[0]

        def good_rebind(self, cache, toks):
            logits, cache = self._decode(self.params, cache, toks)
            return logits, cache[0]
    """})
    found = [f for f in run_checker(donation_safety.check, project)
             if f.rule == rules.DONATION_READ_AFTER_DONATE]
    assert [f.symbol for f in found] == ["Eng.bad_read"]
    assert "donated argument position 1" in found[0].message


# ==================================== repo mutation fixtures
# (reverting any of this PR's true-positive fixes fails `make lint`
# with the new family's finding name)


def test_mutation_multihost_discarded_reservation_put():
    """Revert the _form fix: drop the reservation-write verdict check
    back to a bare fenced-write statement -> fence-result-ignored."""
    found, graph = mutant_findings(
        fence_safety.check, "ray_tpu/core/multihost.py",
        """                if not (stub.mh_group_put(self.group_id, "reservation",
                                          sub["reservation_id"],
                                          int(reg["epoch"]),
                                          timeout=dl.remaining())
                        or {}).get("ok"):
                    raise GroupEpochFenced(
                        f"reservation write for group {self.group_id} "
                        "rejected: a newer registration owns the epoch")""",
        """                stub.mh_group_put(self.group_id, "reservation",
                                  sub["reservation_id"],
                                  int(reg["epoch"]))""")
    hits = [f for f in found if f.rule == rules.FENCE_RESULT_IGNORED
            and f.path == "ray_tpu/core/multihost.py"]
    assert hits and hits[0].symbol == "HostGroup._form"
    # --diff slice coverage: the finding is in the changed file's
    # slice, and absent from an unrelated file's slice.
    sliced = fence_safety.check(
        graph, emit_files={"ray_tpu/core/multihost.py"})
    assert any(f.rule == rules.FENCE_RESULT_IGNORED for f in sliced)
    assert _pragma_filtered(
        fence_safety.check(graph, emit_files={"ray_tpu/autopilot.py"}),
        graph.project) == []


def test_mutation_multihost_compare_flip():
    """Flip the registry's strictly-older-loses guards to <= -> every
    flipped site is an epoch-compare-direction finding."""
    found, _ = mutant_findings(
        fence_safety.check, "ray_tpu/core/multihost.py",
        "if epoch < rec.epoch:", "if epoch <= rec.epoch:")
    hits = [f for f in found
            if f.rule == rules.FENCE_COMPARE_DIRECTION]
    assert len(hits) >= 1
    assert all(f.path == "ray_tpu/core/multihost.py" for f in hits)


def test_mutation_controller_unfenced_save():
    """Revert the fenced checkpoint write to raw kv_put ->
    unfenced-mutation-in-fenced-class at _save_state."""
    found, _ = mutant_findings(
        fence_safety.check, "ray_tpu/serve/controller.py",
        "kv_put_fenced(", "kv_put(")
    hits = [f for f in found
            if f.rule == rules.FENCE_UNFENCED_MUTATION]
    assert hits and hits[0].path == "ray_tpu/serve/controller.py"
    assert "ServeController" in hits[0].message


def test_mutation_controller_snapshot_epoch_dropped():
    """Drop the routing snapshot's epoch stamp -> epoch-not-threaded
    at the _publish psub_publish site (routers would fence blind)."""
    found, _ = mutant_findings(
        fence_safety.check, "ray_tpu/serve/controller.py",
        '"epoch": self._epoch,', "")
    hits = [f for f in found
            if f.rule == rules.FENCE_EPOCH_NOT_THREADED]
    assert hits and hits[0].symbol == "ServeController._publish"


def test_mutation_decode_unwrapped_dispatch():
    """Unwrap a donated program from its _dispatch_fresh guard ->
    donation-unguarded-dispatch (the PR 14 reload footgun reopened)."""
    found, graph = mutant_findings(
        donation_safety.check, "ray_tpu/serve/decode.py",
        """toks_dev, self.cache = self._dispatch_fresh(
                ("decode_sampled",),
                lambda: self._decode_sampled(
                    self.params, self.cache, tin, jnp.asarray(temps),
                    jnp.asarray(self.steps, jnp.int32)))""",
        """toks_dev, self.cache = self._decode_sampled(
                self.params, self.cache, tin, jnp.asarray(temps),
                jnp.asarray(self.steps, jnp.int32))""")
    hits = [f for f in found if f.rule == rules.DONATION_UNGUARDED]
    assert hits and hits[0].path == "ray_tpu/serve/decode.py"
    assert "_decode_sampled" in hits[0].message
    # --diff slice coverage for the donation family
    sliced = donation_safety.check(
        graph, emit_files={"ray_tpu/serve/decode.py"})
    assert any(f.rule == rules.DONATION_UNGUARDED for f in sliced)
    assert donation_safety.check(
        graph, emit_files={"ray_tpu/core/multihost.py"}) == []


def test_mutation_decode_asarray_flip():
    """Flip a draft-token copy back to np.asarray ->
    donation-asarray-alias (the PR 16 clobbered-tokens bug)."""
    found, _ = mutant_findings(
        donation_safety.check, "ray_tpu/serve/decode.py",
        "toks_d = np.array(toks_d)", "toks_d = np.asarray(toks_d)")
    hits = [f for f in found
            if f.rule == rules.DONATION_ASARRAY_ALIAS]
    assert hits and hits[0].path == "ray_tpu/serve/decode.py"
    assert "np.array" in hits[0].message


# ======================================= collector-liveness guards


def test_fenced_site_collector_sees_the_repo():
    """The fenced-write site collector still finds the real protocol
    sites — if an API rename emptied it, the family would read clean
    while checking nothing."""
    sites = fence_safety._fenced_call_sites(_repo_graph())
    apis = {api for _c, _i, api in sites}
    assert {"kv_put_fenced", "mh_group_put", "psub_publish"} <= apis
    paths = {info.file.relpath for _c, info, _a in sites}
    assert "ray_tpu/serve/controller.py" in paths
    assert "ray_tpu/core/multihost.py" in paths


def test_donation_index_sees_the_repo():
    """The donation index still maps the decode engine's donated
    programs (donate_argnums recognized through _mesh_scoped-style
    wrappers)."""
    index = donation_safety._Index(_repo_graph())
    assert ("ray_tpu.serve.decode", "DecodeEngine") \
        in index.owner_classes
    attrs = {attr for (mod, cls, attr) in index.donated_attrs
             if mod == "ray_tpu.serve.decode"}
    assert "_decode" in attrs
    assert len(attrs) >= 6, sorted(attrs)


# ============================= repo-clean gates + strict-path wiring


def test_fence_family_repo_clean():
    found = _pragma_filtered(fence_safety.check(_repo_graph()),
                             _base_project())
    assert found == [], "\n".join(f.render() for f in found)


def test_donation_family_repo_clean():
    found = _pragma_filtered(donation_safety.check(_repo_graph()),
                             _base_project())
    assert found == [], "\n".join(f.render() for f in found)


def test_strict_path_covers_new_families():
    """run_analysis (the `make lint` path) runs both new families:
    their timings land in stats and the repo is clean through the
    full pragma/fingerprint pipeline under the EMPTY baseline."""
    findings, stats = run_analysis(
        select=sorted(FENCE_RULES | DONATION_RULES))
    assert findings == [], "\n".join(f.render() for f in findings)
    assert "fence-safety_s" in stats
    assert "donation-aliasing_s" in stats
