"""MoE expert parallelism + Ulysses sequence parallelism on the virtual
8-device mesh (SURVEY §2.4 EP row, §5.7 Ulysses)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.ops import moe
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.parallel.sharding import axis_rules


def test_router_topk_full_capacity_matches_dense():
    # With capacity >= tokens and k == E, MoE degenerates to a softmax
    # mixture of all experts — compare against the dense computation.
    t, e = 16, 4
    logits = jax.random.normal(jax.random.key(0), (t, e))
    dispatch, combine = moe.router_topk(logits, k=e, capacity=t)
    probs = jax.nn.softmax(logits, axis=-1)
    # combine summed over capacity = gate weight per (token, expert)
    np.testing.assert_allclose(np.asarray(combine.sum(-1)),
                               np.asarray(probs), rtol=1e-5, atol=1e-5)
    # every token dispatched exactly e times
    assert int(dispatch.sum()) == t * e


def test_moe_ffn_runs_and_balances():
    d, m, e = 32, 64, 4
    params = moe.init_moe_params(jax.random.key(1), d, m, e)
    x = jax.random.normal(jax.random.key(2), (2, 16, d), jnp.float32)
    out, aux = moe.moe_ffn(x, params, top_k=2, capacity_factor=2.0)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0.0


def test_moe_llama_trains_on_expert_mesh():
    cfg = dataclasses.replace(
        llama.PRESETS["debug"], moe_experts=4, moe_top_k=2)
    mesh = MeshSpec(data=2, expert=4).build()
    params = llama.init_params(cfg, jax.random.key(0))
    from ray_tpu.parallel import train_step as ts

    params = ts.init_sharded_params(
        lambda k: llama.init_params(cfg, k), llama.param_axes(cfg), mesh,
        jax.random.key(0))
    import optax

    opt = optax.adamw(1e-3)
    opt_state = ts.init_optimizer_state(opt, params)
    step = ts.build_train_step(lambda p, b: llama.loss_fn(p, b, cfg), opt,
                               mesh)
    tokens = ts.shard_batch(
        {"tokens": jax.random.randint(jax.random.key(1), (4, 65), 0,
                                      cfg.vocab_size)}, mesh)
    losses = []
    for _ in range(4):
        params, opt_state, metrics = step(params, opt_state, tokens)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses  # it learns (overfits one batch)


def test_ulysses_matches_full_attention():
    from ray_tpu.parallel.ulysses import ulysses_attention

    mesh = MeshSpec(seq=4).build()
    b, s, h, dd = 2, 64, 8, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, dd), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, h, dd), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, h, dd), jnp.float32)
    from ray_tpu.ops.attention import attention

    expect = attention(q, k, v, causal=True)
    got = ulysses_attention(q, k, v, mesh, impl="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-4)
