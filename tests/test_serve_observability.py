"""Serve-plane observability (ISSUE 9): SLO metric instruments and
aggregation, metrics-flusher robustness across controller death, engine
step timeline, request spans (queue-wait / prefill / decode /
outcome), trace context surviving a router retry, the proxy's
/metrics route, serve.status() SLO summaries, and the
metrics-name-collision lint family. Engine-level tests use tiny CPU
configs; cluster tests use the in-process fixture."""

import json
import textwrap
import threading
import time
import urllib.request
import uuid

import numpy as np
import pytest

from ray_tpu.util.metrics import (Counter, Histogram, _Registry,
                                  counter_totals, histogram_quantile,
                                  histogram_summary, merge_histograms,
                                  prometheus_text)


def _tiny(max_seq_len=256):
    import jax

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, mlp_dim=64,
                            max_seq_len=max_seq_len)
    params = llama.init_params(cfg, jax.random.key(0))
    return cfg, params


def _drive(eng, reqs, budget=400):
    for _ in range(budget):
        if all(r.done.is_set() for r in reqs):
            return
        eng.step()
    raise AssertionError(f"not done: {[r.status for r in reqs]}")


def _snap(name, deployment):
    """This process's registry entries for one metric + deployment."""
    return [m for m in _Registry.get().snapshot()
            if m["name"] == name
            and m["tags"].get("deployment") == deployment]


# ------------------------------------------------------ registry units


def test_observe_many_matches_repeated_observe():
    dep_a, dep_b = f"a-{uuid.uuid4().hex[:6]}", f"b-{uuid.uuid4().hex[:6]}"
    h = Histogram("obs_many_test_s", boundaries=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v, {"deployment": dep_a})
    h.observe_many((0.05, 0.5, 5.0, 50.0), {"deployment": dep_b})
    a = _snap("obs_many_test_s", dep_a)[0]
    b = _snap("obs_many_test_s", dep_b)[0]
    assert a["counts"] == b["counts"] == [1, 1, 1, 1]
    assert a["sum"] == b["sum"] and a["count"] == b["count"] == 4


def test_prometheus_text_emits_cumulative_bucket_ladder():
    dep = f"p-{uuid.uuid4().hex[:6]}"
    h = Histogram("prom_bucket_test_s", boundaries=(0.1, 1.0))
    h.observe_many((0.05, 0.5, 5.0), {"deployment": dep})
    text = prometheus_text({"src": _snap("prom_bucket_test_s", dep)})
    lines = [ln for ln in text.splitlines() if dep in ln]
    assert any('le="0.1"} 1' in ln for ln in lines), lines
    assert any('le="1.0"} 2' in ln for ln in lines), lines
    assert any('le="+Inf"} 3' in ln for ln in lines), lines
    assert any(ln.startswith("prom_bucket_test_s_sum") for ln in lines)
    assert any(ln.startswith("prom_bucket_test_s_count")
               and ln.endswith(" 3") for ln in lines)


def test_histogram_quantile_interpolates_and_clamps():
    entry = {"buckets": [0.1, 1.0, 10.0], "counts": [0, 10, 0, 2],
             "sum": 7.0, "count": 12}
    # p50 -> rank 6 of the 10 obs spread across (0.1, 1.0].
    q50 = histogram_quantile(entry, 0.5)
    assert 0.1 < q50 <= 1.0
    # p99 lands in the +Inf bucket: clamps to the top finite edge.
    assert histogram_quantile(entry, 0.99) == 10.0
    assert histogram_quantile({"buckets": [1], "counts": [0, 0],
                               "sum": 0, "count": 0}, 0.5) is None
    s = histogram_summary(entry)
    assert s["count"] == 12 and s["p50"] == q50


def test_merge_histograms_across_sources_and_slo_summary():
    from ray_tpu.serve.metrics import slo_summary

    dep = f"m-{uuid.uuid4().hex[:6]}"
    entry = {"name": "serve_ttft_s", "kind": "histogram",
             "tags": {"deployment": dep}, "buckets": [0.1, 1.0],
             "counts": [1, 1, 0], "sum": 0.6, "count": 2}
    other = dict(entry, counts=[0, 0, 1], sum=5.0, count=1)
    agg = {"w1": [entry], "w2": [other],
           "w3": [{"name": "serve_requests_total", "kind": "counter",
                   "tags": {"deployment": dep, "outcome": "completed"},
                   "value": 2.0},
                  {"name": "serve_requests_total", "kind": "counter",
                   "tags": {"deployment": dep, "outcome": "shed"},
                   "value": 1.0}]}
    merged = merge_histograms(agg, "serve_ttft_s")
    key = (("deployment", dep),)
    assert merged[key]["count"] == 3
    assert merged[key]["counts"] == [1, 1, 1]
    totals = counter_totals(agg, "serve_requests_total")
    assert totals[(("deployment", dep), ("outcome", "completed"))] == 2.0
    slo = slo_summary(agg)
    assert slo[dep]["ttft_s"]["count"] == 3
    assert slo[dep]["outcomes"] == {"completed": 2, "shed": 1}


# ------------------------------------------- flusher fault tolerance


class _StubController:
    """Controller double: notify() fails while .dead, else stores the
    latest snapshot per source (exactly the real push_metrics shape)."""

    def __init__(self):
        self.dead = True
        self.pushes = 0
        self.latest = None
        self.lock = threading.Lock()

    def notify(self, method, source, snapshot):
        assert method == "push_metrics"
        with self.lock:
            if self.dead:
                raise ConnectionError("controller down")
            self.pushes += 1
            self.latest = snapshot


class _StubCore:
    class _Id:
        def binary(self):
            return b"x" * 8

    def __init__(self):
        self.controller = _StubController()
        self.node_id = self._Id()
        self.worker_id = self._Id()


def test_metrics_flusher_survives_controller_death(monkeypatch):
    """The flusher thread must outlive a dead/restarting controller,
    and because pushes are CUMULATIVE snapshots, a reconnect must not
    double-count anything recorded during the outage."""
    from ray_tpu.core import runtime
    from ray_tpu.core.config import config as rt_config

    stub = _StubCore()
    monkeypatch.setattr(runtime, "_core_worker", stub)
    monkeypatch.setattr(rt_config, "metrics_flush_interval_s", 0.05)

    dep = f"f-{uuid.uuid4().hex[:6]}"
    c = Counter("flush_ft_test_total")
    c.inc(3.0, {"deployment": dep})  # starts/kicks the flusher
    reg = _Registry.get()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:  # let it FAIL a few times
        if reg._flusher is not None:
            time.sleep(0.3)
            break
        time.sleep(0.01)
    assert reg._flusher is not None and reg._flusher.is_alive()

    c.inc(2.0, {"deployment": dep})  # recorded DURING the outage
    with stub.controller.lock:
        stub.controller.dead = False  # controller "restarts"
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with stub.controller.lock:
            if stub.controller.pushes >= 2:
                break
        time.sleep(0.05)
    with stub.controller.lock:
        assert stub.controller.pushes >= 1, "no push after reconnect"
        mine = [m for m in stub.controller.latest
                if m["name"] == "flush_ft_test_total"
                and m["tags"].get("deployment") == dep]
    # 3 + 2 exactly once — the snapshot supersedes, never adds.
    assert mine and mine[0]["value"] == 5.0
    assert reg._flusher.is_alive()
    assert reg.flush_now()  # synchronous path works against the stub
    monkeypatch.setattr(rt_config, "metrics_flush_interval_s", 5.0)


# ------------------------------------------------- engine instruments


def test_engine_terminal_metrics_and_queue_wait():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    dep = f"eng-{uuid.uuid4().hex[:6]}"
    eng = DecodeEngine(params, cfg, slots=2, capacity=128,
                       prefix_pool_entries=0, queue_max=3,
                       metrics_deployment=dep)
    done = [eng.submit([1, 2, 3], max_new_tokens=4) for _ in range(2)]
    _drive(eng, done)
    # Cancelled-in-queue and shed outcomes (no steps between submits,
    # so everything stays pending until the drain below).
    eng.submit([1] * 4, max_new_tokens=8)
    eng.submit([2] * 4, max_new_tokens=8)
    victim = eng.submit([3] * 4, max_new_tokens=4, deadline_s=30.0)
    eng.cancel(victim.request_id)
    from ray_tpu.core.errors import OverloadedError

    with pytest.raises(OverloadedError):
        for _ in range(8):
            eng.submit([4] * 4, max_new_tokens=4)
    for _ in range(200):
        eng.step()
    totals = counter_totals({"local": _Registry.get().snapshot()},
                            "serve_requests_total")

    def outcome(o):
        return totals.get((("deployment", dep), ("outcome", o)), 0)

    assert outcome("completed") >= 2
    assert outcome("cancelled") >= 1
    assert outcome("shed") >= 1
    ttft = _snap("serve_ttft_s", dep)[0]
    assert ttft["count"] >= 2
    itl = _snap("serve_inter_token_s", dep)[0]
    assert itl["count"] >= 2
    qw = _snap("serve_queue_wait_s", dep)[0]
    assert qw["count"] >= 2
    eng.shutdown()


def test_engine_spans_attach_to_request_trace(ray_start_regular):
    """Spans recorded by the engine's LOOP thread land under the trace
    captured at submit(): queue-wait, prefill, decode, and the
    engine-request outcome span all share the submitting trace."""
    from ray_tpu.serve.decode import DecodeEngine
    from ray_tpu.util import tracing

    core = ray_start_regular
    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=128,
                       prefix_pool_entries=0)
    with tracing.trace("submit-root") as (trace_id, _):
        req = eng.submit([1, 2, 3, 4], max_new_tokens=4)
    _drive(eng, [req])
    eng.shutdown()
    deadline = time.monotonic() + 30
    names = set()
    while time.monotonic() < deadline:
        core._flush_task_events()
        events = core.controller.call("list_task_events", 10000)
        names = {e["desc"] for e in events
                 if e.get("state") == "SPAN"
                 and e.get("trace_id") == trace_id}
        if {"queue-wait", "prefill", "decode",
                "engine-request"} <= names:
            break
        time.sleep(0.2)
    assert {"queue-wait", "prefill", "decode",
            "engine-request"} <= names, names
    outcome = [e for e in events if e.get("state") == "SPAN"
               and e.get("trace_id") == trace_id
               and e["desc"] == "engine-request"]
    assert outcome[0]["attrs"]["outcome"] == "completed"
    assert outcome[0]["attrs"]["tokens"] == 4


# ----------------------------------------------------- step timeline


def test_step_timeline_ring_bounded_with_phases_and_compiles():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=128,
                       prefix_pool_entries=0, step_timeline=8)
    reqs = [eng.submit([1, 2, 3], max_new_tokens=32) for _ in range(2)]
    _drive(eng, reqs, budget=200)
    tl = eng.timeline()
    assert len(tl["rows"]) <= 8
    assert tl["dropped"] > 0  # 32+ steps through an 8-row ring
    phases = {p["phase"] for row in tl["rows"] for p in row["phases"]}
    assert "decode" in phases
    row = tl["rows"][-1]
    assert {"step", "t0", "t1", "active", "prefilling",
            "queued"} <= set(row)
    eng.shutdown()
    # jit-compile events fired for first dispatches (admit ran inside
    # the ring window on the first steps — check the engine saw them).
    assert ("decode",) in eng._compiled


def test_step_timeline_disabled_is_free():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=1, capacity=128,
                       prefix_pool_entries=0, step_timeline=0,
                       metrics_enabled=False, trace_spans=False)
    req = eng.submit([1, 2, 3], max_new_tokens=4)
    _drive(eng, [req])
    assert eng.timeline()["rows"] == []
    assert not eng.steplog.enabled
    eng.shutdown()


def test_paged_timeline_records_page_events_and_preempt_counter():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny(max_seq_len=512)
    dep = f"pre-{uuid.uuid4().hex[:6]}"
    rng = np.random.default_rng(7)
    eng = DecodeEngine(params, cfg, slots=4, capacity=256,
                       page_tokens=16, pool_pages=20,
                       prefix_pool_entries=0, step_timeline=4096,
                       metrics_deployment=dep)
    prompts = [rng.integers(0, cfg.vocab_size, 30).tolist()
               for _ in range(4)]
    reqs = [eng.submit(p, max_new_tokens=90) for p in prompts]
    _drive(eng, reqs, budget=3000)
    assert eng.preempted > 0
    kinds = {e["kind"] for row in eng.timeline()["rows"]
             for e in row.get("events", [])}
    assert {"page-alloc", "page-free", "preempt"} <= kinds, kinds
    totals = counter_totals({"local": _Registry.get().snapshot()},
                            "serve_preemptions_total")
    assert totals.get((("deployment", dep),), 0) == eng.preempted
    rows = eng.timeline()["rows"]
    assert any(r.get("pages_free") is not None for r in rows)
    from ray_tpu.serve.steplog import timeline_chrome_events

    ev = timeline_chrome_events(eng.timeline(), pid="engine:t")
    assert any(e["ph"] == "i" and e["name"] == "preempt" for e in ev)
    eng.shutdown()


# ------------------------------------------------ router retry traces


def test_trace_context_survives_router_retry(ray_start_regular):
    """A replica death mid-request retries onto a survivor; both
    attempt spans parent under the SAME router span (one request, one
    trace), tagged with their attempt ordinal and replica."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.deployment import _Router
    from ray_tpu.util import tracing

    core = ray_start_regular

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), name="retry_trace")
    try:
        router = _Router.get("retry_trace")
        with router._lock:
            assert len(router._replicas) == 2
            dead = router._replicas[0]
        ray_tpu.kill(dead["handle"])
        time.sleep(0.5)  # let the kill land (calls now ActorDied)

        orig_pick = router._pick
        picked = {"n": 0}

        def pick_dead_first(model_id, prefix_hashes=None):
            picked["n"] += 1
            if picked["n"] == 1:
                with router._lock:
                    router._inflight[dead["id"]] = (
                        router._inflight.get(dead["id"], 0) + 1)
                return dead
            return orig_pick(model_id, prefix_hashes)

        router._pick = pick_dead_first
        try:
            with tracing.trace("retry-root") as (trace_id, _):
                assert handle.remote(7).result(timeout=60) == 7
        finally:
            router._pick = orig_pick
        assert picked["n"] >= 2, "retry never happened"

        deadline = time.monotonic() + 30
        attempts, router_spans = [], []
        while time.monotonic() < deadline:
            core._flush_task_events()
            events = core.controller.call("list_task_events", 10000)
            spans = [e for e in events if e.get("state") == "SPAN"
                     and e.get("trace_id") == trace_id]
            attempts = sorted(
                (e for e in spans if e["desc"] == "attempt"),
                key=lambda e: e["attrs"]["attempt"])
            router_spans = [e for e in spans
                            if e["desc"] == "router:retry_trace"]
            if len(attempts) >= 2 and router_spans:
                break
            time.sleep(0.2)
        assert len(attempts) >= 2, "expected a retried attempt span"
        assert router_spans, "no router span"
        parent = router_spans[0]["span_id"]
        assert all(a["parent_span"] == parent for a in attempts[:2])
        assert attempts[0]["attrs"]["attempt"] == 0
        assert attempts[1]["attrs"]["attempt"] == 1
        assert (attempts[0]["attrs"]["replica"]
                != attempts[1]["attrs"]["replica"])
    finally:
        serve.delete("retry_trace")


# ------------------------------- proxy /metrics + status slo (e2e)


@pytest.mark.slow  # 7s: full proxy metrics sweep; PR 16 rebudget
def test_proxy_metrics_route_and_status_slo(ray_start_regular):
    """One decode deployment behind the real HTTP proxy: /metrics
    serves Prometheus text with per-deployment TTFT and inter-token
    bucket ladders, and serve.status() carries the same numbers as
    slo summaries (one aggregation path)."""
    from ray_tpu import serve
    from ray_tpu.serve.decode import LlamaDecodeDeployment

    app = serve.deployment(LlamaDecodeDeployment).bind(
        preset="debug", slots=2, capacity=128)
    serve.run(app, name="slo_app")
    try:
        host, port = serve.start_http()
        url = f"http://{host}:{port}/slo_app"
        for i in range(2):
            req = urllib.request.Request(
                url, data=json.dumps({"tokens": [1, 2, 3 + i],
                                      "max_new_tokens": 4}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
                assert len(out["tokens"]) == 4

        # Replica + proxy flushers push every ~5 s; poll the route.
        def _dep_lines(text, metric):
            return [ln for ln in text.splitlines()
                    if ln.startswith(metric)
                    and 'deployment="slo_app"' in ln]

        deadline = time.monotonic() + 30
        text = ""
        while time.monotonic() < deadline:
            with urllib.request.urlopen(f"http://{host}:{port}/metrics",
                                        timeout=30) as resp:
                assert resp.status == 200
                text = resp.read().decode()
            if (_dep_lines(text, "serve_ttft_s_bucket")
                    and _dep_lines(text, "serve_inter_token_s_bucket")
                    and _dep_lines(text, "serve_http_requests_total")):
                break
            time.sleep(0.5)
        # Per-DEPLOYMENT TTFT and inter-token bucket ladders: the
        # engine inside the replica labeled its observations with the
        # deployment it serves (replica identity threaded at spawn).
        assert _dep_lines(text, "serve_ttft_s_bucket"), text[:2000]
        assert _dep_lines(text, "serve_inter_token_s_bucket")
        assert any('le="+Inf"' in ln
                   for ln in _dep_lines(text, "serve_ttft_s_bucket"))
        assert _dep_lines(text, "serve_queue_wait_s_count")

        deadline = time.monotonic() + 15
        slo = {}
        while time.monotonic() < deadline:
            slo = serve.status()["slo_app"].get("slo", {})
            if slo.get("ttft_s", {}).get("count", 0) >= 2:
                break
            time.sleep(0.5)
        assert slo["ttft_s"]["count"] >= 2
        assert slo["ttft_s"]["p50"] is not None
        assert slo["inter_token_s"]["count"] >= 2
        assert slo["outcomes"].get("completed", 0) >= 2
        assert slo["http_responses"].get("200", 0) >= 2

        # Dashboard agreement: same aggregation helper, same numbers.
        from ray_tpu.core.runtime import get_core_worker
        from ray_tpu.serve.metrics import slo_summary

        agg = get_core_worker().controller.call("list_metrics")
        assert (slo_summary(agg)["slo_app"]["ttft_s"]["count"]
                >= slo["ttft_s"]["count"] - 1)
    finally:
        serve.shutdown()


# --------------------------------------------- metrics-name-collision


def _lint_project(**modules):
    from ray_tpu.analysis.core import Project, SourceFile

    files = []
    for name, src in modules.items():
        rel = f"ray_tpu/{name}.py"
        files.append(SourceFile(f"/fixture/{rel}", rel,
                                textwrap.dedent(src)))
    return Project("/fixture", files)


def _run_metrics_lint(project):
    from ray_tpu.analysis import metrics_lint

    by_rel = {f.relpath: f for f in project.files}
    return [f for f in metrics_lint.check_project(project)
            if not by_rel[f.path].suppressed(f.rule, f.line)]


def test_metrics_lint_flags_kind_and_bucket_collisions():
    project = _lint_project(
        a="""
        from ray_tpu.util.metrics import Counter, Histogram
        REQS = Counter("svc_requests_total")
        LAT = Histogram("svc_latency_s", "d", boundaries=(0.1, 1.0))
        """,
        b="""
        from ray_tpu.util import metrics
        BAD_KIND = metrics.Gauge("svc_requests_total")
        BAD_GRID = metrics.Histogram("svc_latency_s", "d",
                                     boundaries=(0.5, 5.0))
        """)
    findings = _run_metrics_lint(project)
    assert len(findings) == 2
    msgs = " | ".join(f.message for f in findings)
    assert "one name, one kind" in msgs
    assert "bucket boundaries" in msgs
    assert all(f.path == "ray_tpu/b.py" for f in findings)


def test_metrics_lint_true_negatives():
    project = _lint_project(
        a="""
        from ray_tpu.util.metrics import Counter, Histogram
        GRID = (0.1, 1.0)
        A = Counter("tn_total")
        H1 = Histogram("tn_lat_s", "d", boundaries=GRID)
        """,
        b="""
        from collections import Counter  # NOT the metrics class
        from ray_tpu.util.metrics import Counter as MCounter, Histogram
        c = Counter("tn_total some text".split())  # stdlib: ignored
        B = MCounter("tn_total")                   # same kind: fine
        H2 = Histogram("tn_lat_s", "d", boundaries=GRID)  # same grid
        """)
    assert _run_metrics_lint(project) == []


def test_metrics_lint_repo_is_clean():
    from ray_tpu.analysis import repo_root, run_analysis

    findings, _stats = run_analysis(
        root=repo_root(), select=["metrics-name-collision"], jobs=1)
    assert findings == [], [f.render() for f in findings]


# ----------------------------------------------- timeline CLI builder


def test_build_chrome_trace_links_and_engine_merge():
    from ray_tpu.scripts import build_chrome_trace

    t0 = 1000.0
    events = [
        {"task_id": "s1", "desc": "parent", "state": "SPAN",
         "trace_id": "t", "span_id": "s1", "parent_span": None,
         "lease_ts": t0, "end_ts": t0 + 1, "owner": "procA",
         "worker": "wa"},
        {"task_id": "s2", "desc": "child", "state": "SPAN",
         "trace_id": "t", "span_id": "s2", "parent_span": "s1",
         "lease_ts": t0 + 0.1, "end_ts": t0 + 0.9, "owner": "procB",
         "worker": "wb", "attrs": {"attempt": 0}},
        {"task_id": "x", "desc": "task", "state": "FINISHED",
         "lease_ts": t0, "end_ts": t0 + 0.5, "owner": "procB",
         "worker": "wb"},
    ]
    timelines = {"dep": {"dep#0": {"rows": [
        {"step": 1, "t0": t0, "t1": t0 + 0.01,
         "phases": [{"phase": "decode", "t0": t0, "t1": t0 + 0.01,
                     "batch": 2, "k": 1}],
         "active": 2, "prefilling": 0, "queued": 0,
         "events": [{"kind": "page-alloc", "ts": t0, "n": 1}]},
    ]}}}
    trace = build_chrome_trace(events, timelines)
    txt = json.dumps(trace)  # must be JSON-serializable
    assert json.loads(txt)
    spans = [t for t in trace if t.get("cat") == "span"]
    assert {s["args"]["span_id"] for s in spans} == {"s1", "s2"}
    child = next(s for s in spans if s["args"]["span_id"] == "s2")
    assert child["args"]["parent_span"] == "s1"
    assert child["args"]["attempt"] == 0
    flows = [t for t in trace if t.get("cat") == "flow"]
    assert {f["ph"] for f in flows} == {"s", "f"}
    engine = [t for t in trace if t.get("cat") == "engine-step"]
    assert engine and engine[0]["pid"] == "engine:dep#0"
    assert any(t.get("ph") == "M" for t in trace)  # process_name meta
    from ray_tpu.serve.trace_demo import validate_trace

    report = validate_trace(trace)
    assert report["cross_process_links"] == [("parent", "child")]
    assert report["engine_slices"] == 1
