"""Multi-host gang contract (ISSUE 13, ROADMAP #3).

The CPU box cannot run multiprocess collectives (jaxlib 0.4.37), so
these tests prove everything AROUND the collective: gang spawn and
teardown with aligned member contexts, one-member-death reconciling the
WHOLE group (sub-slice released exactly once), coordinator failover
with epoch fencing (the deposed coordinator's stale-epoch write is
rejected), zombie-member self-fencing, program-hash mismatch as a typed
refusal (no hang), all-or-nothing placement refusal feeding the
autoscaler's pending demand, single-process parity (a 1-host group's
decode is bit-identical to calling the engine directly), and the
doctor's gang-hang signature driven off the new multihost metrics via
util/faultinject.

Budget-conscious: ONE module-scoped cluster (a single dev-box node
advertising a virtual multi-host slice — 4x4 grid, 4 chips per host =
4 virtual hosts) shared by every test.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.core import multihost
from ray_tpu.core.config import config
from ray_tpu.core.multihost import (GangPlacementError, HostGroup,
                                    member_name)
from ray_tpu.core.placement import cluster_topology
from ray_tpu.core.rpc_stubs import ControllerStub
from ray_tpu.core.runtime import get_core_worker
from ray_tpu.util import faultinject
from ray_tpu.util.faultinject import Faults
from ray_tpu.util.metrics import _Registry

_FAULTS = "/tmp/ray_tpu_mh_faults.json"


@pytest.fixture(scope="module")
def mh_cluster(tmp_path_factory):
    """One cluster for the whole module: a virtual 4-host slice (4x4
    grid / 4 chips per host) with fault injection AND the flight
    recorder plumbed into every process (env set BEFORE init so
    workers inherit both; a per-run recorder dir keeps stale fr-<pid>
    files from other sessions out of the post-mortem)."""
    fr_dir = str(tmp_path_factory.mktemp("flightrec"))
    saved = {k: os.environ.get(k)
             for k in ("RAY_TPU_VIRTUAL_SLICE", "RAY_TPU_FAULTINJECT_PATH",
                       "RAY_TPU_FLIGHTREC_DIR")}
    os.environ["RAY_TPU_VIRTUAL_SLICE"] = "4x4/4"
    os.environ["RAY_TPU_FAULTINJECT_PATH"] = _FAULTS
    os.environ["RAY_TPU_FLIGHTREC_DIR"] = fr_dir
    old_path = config.faultinject_path
    old_fr = config.flightrec_dir
    config.faultinject_path = _FAULTS
    config.flightrec_dir = fr_dir
    faultinject.reset_counters()
    core = ray_tpu.init(num_cpus=8)
    yield core
    ray_tpu.shutdown()
    config.faultinject_path = old_path
    config.flightrec_dir = old_fr
    faultinject.reset_counters()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _reservations():
    slices = cluster_topology()["slices"]
    out = {}
    for s in slices.values():
        out.update(s["reservations"])
    return out


def _wait_for(pred, timeout=45.0, period=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(period)
    return False


# ------------------------------------------------ gang spawn/teardown


def test_gang_spawn_alignment_and_teardown(mh_cluster):
    """Formation hands every member the SAME group geometry and a
    disjoint chip mask covering the sub-slice; teardown releases the
    reservation exactly once and drops the group record."""
    g = HostGroup(2, name="form-gang").start()
    try:
        assert g.state == "ALIVE" and g.epoch == 1
        infos = g.call_all("member_info", timeout=30.0)
        # Aligned visibility: same coordinator/num_processes/epoch,
        # process ids 0..n-1, member names per convention.
        coords = {i["coordinator_address"] for i in infos}
        assert len(coords) == 1 and None not in coords
        assert [i["process_id"] for i in infos] == [0, 1]
        assert {i["num_processes"] for i in infos} == {2}
        assert {i["epoch"] for i in infos} == {1}
        assert [i["member"] for i in infos] == ["host-0", "host-1"]
        # Disjoint device masks covering the reserved rectangle.
        masks = [tuple(map(tuple, i["local_device_ids"])) for i in infos]
        assert all(len(m) == 4 for m in masks)
        assert not (set(masks[0]) & set(masks[1]))
        # The election result is in the group's fenced KV.
        coord = g.coordinator()
        assert coord["member"] == "host-0"
        assert coord["address"] in coords
        # Registry shows the group, with the reservation recorded.
        st = multihost.registry_state(g.group_id)
        assert st["num_hosts"] == 2 and st["epoch"] == 1
        assert "coordinator" in st["kv_keys"]
        sub = g.status()["sub_slice"]
        assert sub["reservation_id"] in _reservations()
    finally:
        g.shutdown()
    assert g.status()["releases"] == 1
    assert g.status()["sub_slice"] is None
    assert _reservations() == {}
    assert multihost.registry_state(g.group_id) is None
    # Idempotent: a second shutdown releases nothing further.
    g.shutdown()
    assert g.status()["releases"] == 1


def test_all_or_nothing_refusal_feeds_pending_demand(mh_cluster):
    """A gang no single slice can host is REFUSED before any member
    spawns, and the refusal surfaces as autoscaler pending demand."""
    g = HostGroup(64, name="huge-gang")
    with pytest.raises(GangPlacementError):
        g.start()
    assert g.members == []
    assert _reservations() == {}  # nothing reserved, nothing leaked
    assert multihost.registry_state("huge-gang") is None
    state = ControllerStub(
        get_core_worker().controller).autoscaler_state()
    chips = [d["resources"].get("chips", 0)
             for d in state["pending_demand"]]
    assert 64 * 4 in chips, state["pending_demand"]


# -------------------------------------------- program-hash refusal


def test_program_hash_mismatch_is_typed_refusal(mh_cluster):
    """Mismatched program fingerprints at the pre-collective barrier
    raise ProgramHashMismatch on EVERY member — a typed refusal where
    the collective would have hung."""
    g = HostGroup(2, name="hash-gang").start()
    try:
        t0 = time.monotonic()
        refs = [g.members[0].program_barrier.remote("step", "hashA", 20.0),
                g.members[1].program_barrier.remote("step", "hashB", 20.0)]
        for ref in refs:
            with pytest.raises(Exception) as ei:
                ray_tpu.get(ref, timeout=30.0)
            assert "ProgramHashMismatch" in str(ei.value)
            assert "hashA" in str(ei.value) and "hashB" in str(ei.value)
        # Refusal, not timeout: both members returned well inside the
        # barrier window.
        assert time.monotonic() - t0 < 15.0
        # The group survives a refusal; a matching barrier completes.
        out = g.call_all("program_barrier", "step2", "same", 20.0,
                         timeout=30.0)
        assert all(set(p.values()) == {"same"} for p in out)
    finally:
        g.shutdown()


# ------------------------------------- death + coordinator failover


@pytest.mark.chaos
def test_member_death_reconciles_whole_gang(mh_cluster):
    """SIGKILL one member (faultinject die at its beat site) -> the
    WHOLE gang is killed and re-formed under a bumped epoch; the old
    sub-slice is released exactly once; no old member survives."""
    g = HostGroup(2, name="death-gang", max_group_restarts=1).start()
    try:
        pids = {i["member"]: i["pid"]
                for i in g.call_all("member_info", timeout=30.0)}
        rid_before = g.status()["sub_slice"]["reservation_id"]
        with Faults(_FAULTS) as f:
            f.add("multihost.member.death-gang.host-1.beat", "die",
                  once_global=True, rule_id="kill-h1")
            assert _wait_for(lambda: g.status()["epoch"] == 2
                             and g.status()["state"] == "ALIVE")
        st = g.status()
        assert st["restarts"] == 1
        assert st["releases"] == 1  # the OLD reservation, exactly once
        assert "host-1" in st["death_cause"]
        # Whole-gang semantics: every member is a fresh process.
        pids2 = {i["member"]: i["pid"]
                 for i in g.call_all("member_info", timeout=30.0)}
        assert not (set(pids.values()) & set(pids2.values()))
        assert {i["epoch"] for i in
                g.call_all("member_info", timeout=30.0)} == {2}
        # Old reservation gone; exactly the new one held.
        res = _reservations()
        assert rid_before not in res and len(res) == 1
    finally:
        g.shutdown()
    assert _reservations() == {}


@pytest.mark.chaos
def test_coordinator_failover_and_stale_epoch_fence(mh_cluster):
    """Kill the COORDINATOR: re-election completes under a bumped epoch
    (fresh fenced election record), and the deposed coordinator's
    stale-epoch writes/barrier entries are rejected."""
    g = HostGroup(2, name="coord-gang", max_group_restarts=1).start()
    try:
        assert g.coordinator()["epoch"] == 1
        with Faults(_FAULTS) as f:
            f.add("multihost.member.coord-gang.host-0.beat", "die",
                  once_global=True, rule_id="kill-h0")
            assert _wait_for(lambda: g.status()["epoch"] == 2
                             and g.status()["state"] == "ALIVE")
        st = g.status()
        assert "coordinator" in st["death_cause"]
        # Re-election completed: the fenced record carries the new
        # epoch (a fresh address from the new rank-0 incarnation).
        coord = g.coordinator()
        assert coord["epoch"] == 2 and coord["member"] == "host-0"
        stub = ControllerStub(get_core_worker().controller)
        # The deposed coordinator replays its election write with the
        # old epoch: rejected, not applied.
        put = stub.mh_group_put("coord-gang", "coordinator",
                                {"member": "host-0",
                                 "address": "zombie:1", "epoch": 1}, 1)
        assert put == {"ok": False, "reason": "stale_epoch", "epoch": 2}
        assert g.coordinator()["address"] != "zombie:1"
        # A stale-epoch barrier entry is refused the same way.
        bar = stub.mh_barrier("coord-gang", "zombie-step", "host-0", 1,
                              "h", 5.0)
        assert bar == {"ok": False, "reason": "stale_epoch", "epoch": 2}
        # ISSUE 15: the SAME death explained post-mortem, from flight-
        # recorder dumps alone (doctor.post_mortem is a pure function
        # over the merge — no cluster queries): the killed coordinator
        # is named as the first-dying member (its own recorder file
        # carries the fault.fired die, flushed synchronously before
        # the SIGKILL) and the surviving gang's epoch is on record.
        from ray_tpu import doctor
        from ray_tpu.util import flightrec

        deaths = [x for x in doctor.post_mortem(flightrec.cluster_dump())
                  if x["signature"] == "gang-death"
                  and x["source"] == "group:coord-gang"]
        assert deaths
        d = deaths[0]
        assert d["evidence"]["first_dying"] == "host-0"
        assert d["evidence"]["surviving_epoch"] == 2
        assert d["evidence"]["injected"] is True
        assert "host-0" in d["summary"] and "epoch 2" in d["summary"]
        assert "SIGKILL" in d["summary"]
    finally:
        g.shutdown()


def test_zombie_member_self_fences(mh_cluster):
    """A member of a deposed epoch learns it is fenced from its beat
    and refuses all further group operations (the PR 12 epoch-lease
    idiom at member granularity)."""
    g = HostGroup(1, name="fence-gang").start()
    try:
        member = g.members[0]
        assert ray_tpu.get(member.beat_once.remote(),
                           timeout=10.0)["fenced"] is False
        # A newer incarnation registers (epoch bump) WITHOUT this
        # member: its next beat deposes it.
        _gid, epoch = multihost.register_gang(1, group_id="fence-gang")
        assert epoch == 2
        assert ray_tpu.get(member.beat_once.remote(),
                           timeout=10.0)["fenced"] is True
        info = ray_tpu.get(member.member_info.remote(), timeout=10.0)
        assert info["fenced"] is True
        with pytest.raises(Exception) as ei:
            ray_tpu.get(member.program_barrier.remote("b", "h", 5.0),
                        timeout=10.0)
        assert "GroupEpochFenced" in str(ei.value)
    finally:
        g.shutdown()


# ------------------------------------------ single-process parity


def test_single_host_group_decode_parity(mh_cluster):
    """A 1-host HostGroup running the decode engine produces BIT-
    identical tokens to calling the engine directly in this process —
    the virtual-mesh parity half of the multi-host contract."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.decode import DecodeEngine

    def decode_on_member(member, prompt, n):
        import jax

        from ray_tpu.models import llama
        from ray_tpu.serve.decode import DecodeEngine

        assert member.num_processes == 1 and member.process_id == 0
        # The pre-collective hash check still runs (a 1-host barrier
        # completes immediately) — parity must hold THROUGH the gang
        # path, hash check included.
        member.barrier("parity", "engine-v1", 20.0)
        cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2,
                                n_heads=4, n_kv_heads=2, mlp_dim=64,
                                max_seq_len=128)
        params = llama.init_params(cfg, jax.random.key(0))
        eng = DecodeEngine(params, cfg, slots=2, capacity=64)
        req = eng.submit(list(prompt), max_new_tokens=n)
        for _ in range(200):
            if req.done.is_set():
                break
            eng.step()
        assert req.done.is_set()
        return list(req.output)

    prompt, n = [3, 1, 4, 1, 5], 12
    g = HostGroup(1, name="parity-gang").start()
    try:
        [via_group] = g.broadcast(decode_on_member, prompt, n,
                                  timeout=120.0)
    finally:
        g.shutdown()
    cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2,
                            n_heads=4, n_kv_heads=2, mlp_dim=64,
                            max_seq_len=128)
    params = llama.init_params(cfg, jax.random.key(0))
    eng = DecodeEngine(params, cfg, slots=2, capacity=64)
    req = eng.submit(prompt, max_new_tokens=n)
    for _ in range(200):
        if req.done.is_set():
            break
        eng.step()
    assert req.done.is_set()
    assert via_group == list(req.output)
    assert len(via_group) == n


# ----------------------------------------- doctor: gang-hang


def _agg(source="n1/node/pid1"):
    return {source: _Registry.get().snapshot()}


@pytest.mark.chaos
def test_doctor_names_gang_hang_straggler(mh_cluster):
    """One member's barrier entry is delayed (faultinject at
    multihost.barrier) -> its barrier-entered gauge stays 0 while the
    rest of the gang parks at 1 across the whole window, and the
    doctor names the straggler host."""
    from ray_tpu import doctor

    g = HostGroup(2, name="hang-gang").start()
    refs = []
    try:
        with Faults(_FAULTS) as f:
            f.add("multihost.barrier.hang-gang.host-1", "delay",
                  delay_s=4.0)
            refs = [m.program_barrier.remote("stuck-step", "h", 25.0)
                    for m in g.members]
            # host-0 is parked in the barrier; host-1 is sleeping at
            # the injection point and never arrived.
            assert _wait_for(lambda: (multihost.registry_state(
                "hang-gang")["barriers"].get("stuck-step", {})
                .get("arrived") == ["host-0"]), timeout=10.0)
            before = _agg()
            time.sleep(1.2)
            after = _agg()
        findings = doctor.diagnose(before, after, 1.2)
        hangs = [x for x in findings if x["signature"] == "gang-hang"
                 and "hang-gang" in x["source"]]
        assert hangs, findings
        assert hangs[0]["severity"] == "critical"
        assert "host-1" in hangs[0]["summary"]  # the straggler, named
        assert "host-0" in hangs[0]["summary"]  # who is parked
        # The delay elapses, the straggler arrives, the barrier
        # completes: the "hang" resolves without any intervention...
        assert all(set(p.values()) == {"h"}
                   for p in ray_tpu.get(refs, timeout=60.0))
        # ...and the signature clears (entered gauges uniform again).
        snap = _agg()
        assert [x for x in doctor.diagnose(snap, snap, 1.0)
                if x["signature"] == "gang-hang"] == []
    finally:
        g.shutdown()


# ------------------------------- formation fence verdict (no cluster)


def test_form_aborts_when_reservation_write_is_fenced(monkeypatch):
    """Regression (lint-pinned by graftlint fence-result-ignored): the
    reservation write during formation is a FENCED group-KV write, and
    its verdict must be honored. A stale-epoch rejection means a
    concurrent re-registration already owns the group — spawning
    members against it would form a zombie gang. The fenced refusal
    must abort formation, release the sub-slice exactly once, drop the
    half-registered group record, and spawn nothing."""
    from ray_tpu.core import rpc_stubs
    from ray_tpu.core.multihost import GroupEpochFenced

    calls = []

    class FencingStub:
        def __init__(self, client):
            pass

        def topology_state(self, timeout=None):
            return {"slices": {"s0": {"chips_per_host": 4}}}

        def reserve_subslice(self, owner, chips, timeout=None):
            calls.append(("reserve", chips))
            return {"reservation_id": "res-1", "slice_id": "s0",
                    "nodes": ["n0", "n1"], "origin": [0, 0],
                    "shape": [4, 8]}

        def mh_register_group(self, group_id, num_hosts, res, owner,
                              timeout=None):
            calls.append(("register", group_id))
            return {"epoch": 3}

        def mh_group_put(self, group_id, key, value, epoch,
                         timeout=None):
            calls.append(("put", key, epoch))
            return {"ok": False, "reason": "stale_epoch", "epoch": 4}

        def release_subslice(self, reservation_id, timeout=None):
            calls.append(("release", reservation_id))
            return True

        def mh_drop_group(self, group_id, timeout=None):
            calls.append(("drop", group_id))
            return True

    monkeypatch.setattr(multihost, "_controller_client", lambda: None)
    monkeypatch.setattr(rpc_stubs, "ControllerStub", FencingStub)
    g = HostGroup(2, chips_per_host=4, name="fenced-form")
    with pytest.raises(GroupEpochFenced) as exc:
        g._form()
    assert "rejected" in str(exc.value)
    # the fenced write happened at the observed epoch...
    assert ("put", "reservation", 3) in calls
    # ...and the abort path discharged BOTH leases, spawning nothing
    assert ("release", "res-1") in calls
    assert ("drop", "fenced-form") in calls
    assert g._members == [] and g._sub is None and g._epoch == 0
