"""Memory monitor + OOM worker-killing policy tests.

Reference analogue: ``src/ray/common/memory_monitor.h:52`` and the policy
unit tests for ``worker_killing_policy_retriable_fifo.cc`` /
``worker_killing_policy_group_by_owner.cc``. The policy is tested as a pure
function of a worker-table snapshot; the end-to-end path injects a fake
memory reader into a live node and asserts a running retriable task is
killed and retried.
"""

import time
from types import SimpleNamespace

import pytest

from ray_tpu.core.memory_monitor import pick_victim


def _h(idle=False, dedicated=False, leased=True, retriable=True,
       owner="a", last_used=0.0, alive=True):
    return SimpleNamespace(
        idle=idle,
        dedicated=dedicated,
        lease_resources={"CPU": 1.0} if leased else None,
        task_meta={"retriable": retriable, "owner": owner},
        last_used=last_used,
        proc=SimpleNamespace(poll=lambda: None if alive else 1),
        worker_id=SimpleNamespace(hex=lambda: "w", binary=lambda: b"w"),
    )


def test_policy_idle_workers_die_first():
    idle_old = _h(idle=True, leased=False, last_used=1.0)
    idle_new = _h(idle=True, leased=False, last_used=2.0)
    busy = _h(last_used=3.0)
    assert pick_victim([busy, idle_new, idle_old],
                       "retriable_fifo") is idle_old


def test_policy_retriable_fifo_prefers_newest_retriable():
    old_r = _h(retriable=True, last_used=1.0)
    new_r = _h(retriable=True, last_used=5.0)
    newest_nonr = _h(retriable=False, last_used=9.0)
    assert pick_victim([old_r, newest_nonr, new_r],
                       "retriable_fifo") is new_r
    # Only non-retriable left -> last resort, still newest first.
    assert pick_victim([newest_nonr, _h(retriable=False, last_used=2.0)],
                       "retriable_fifo") is newest_nonr


def test_policy_never_picks_actors_or_dead():
    actor = _h(dedicated=True, last_used=9.0)
    dead = _h(last_used=8.0, alive=False)
    assert pick_victim([actor, dead], "retriable_fifo") is None


def test_policy_group_by_owner_sheds_biggest_group():
    a1 = _h(owner="a", last_used=1.0)
    a2 = _h(owner="a", last_used=4.0)
    b1 = _h(owner="b", last_used=9.0)
    assert pick_victim([a1, b1, a2], "group_by_owner") is a2


@pytest.mark.timeout_s(120)
def test_oom_kill_retries_then_raises(ray_start_regular):
    import ray_tpu
    from ray_tpu.core import api as api_mod

    node = api_mod._local_cluster[1]
    assert node.memory_monitor is not None
    node.memory_monitor.stop()  # drive check_once manually, race-free

    @ray_tpu.remote(max_retries=0)
    def hog():
        time.sleep(60)
        return 1

    ref = hog.remote()
    # Let the lease land, then report the node as over the watermark.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with node._lock:
            if any(h.lease_resources is not None and not h.dedicated
                   for h in node._workers.values()):
                break
        time.sleep(0.05)
    node.memory_monitor.set_reader(lambda: (99, 100))
    killed = None
    deadline = time.monotonic() + 30
    while killed is None and time.monotonic() < deadline:
        killed = node.memory_monitor.check_once()
        time.sleep(0.1)
    assert killed is not None
    with pytest.raises(ray_tpu.OutOfMemoryError):
        ray_tpu.get(ref, timeout=30)
    assert node.get_info()["num_oom_kills"] == 1


@pytest.mark.timeout_s(120)
def test_oom_killed_retriable_task_succeeds_on_retry(ray_start_regular):
    import ray_tpu
    from ray_tpu.core import api as api_mod

    node = api_mod._local_cluster[1]
    node.memory_monitor.stop()  # drive check_once manually, race-free

    @ray_tpu.remote(max_retries=2)
    def quick(x):
        time.sleep(1.0)
        return x + 1

    # Kill the first leased worker once; the resubmission completes.
    ref = quick.remote(41)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        node.memory_monitor.set_reader(lambda: (99, 100))
        if node.memory_monitor.check_once() is not None:
            break
        time.sleep(0.02)
    node.memory_monitor.set_reader(lambda: (0, 100))
    assert ray_tpu.get(ref, timeout=60) == 42
