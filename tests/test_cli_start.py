"""CLI cluster bring-up + job submission + scheduler spillback tests
(reference: ``ray start`` scripts.py:571, ``ray job`` cli.py, hybrid-policy
spillback)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu


def _spawn_daemon(argv, env=None):
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu"] + argv,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": "/root/repo",
             "JAX_PLATFORMS": "cpu", **(env or {})})


def _read_until(proc, marker, timeout=60):
    lines = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.1)
            continue
        lines.append(line)
        if marker in line:
            return line, lines
    raise TimeoutError(f"{marker!r} not seen in {lines}")


@pytest.mark.timeout_s(180)
@pytest.mark.slow
def test_start_head_and_worker_daemons():
    """ray_tpu start --head in one process + a worker joining from another:
    a third process connects as a driver and schedules onto both nodes.

    Slow-marked (PR 14 tier-1 rebudget): 21.2 s, dominated by two full
    daemon interpreter bring-ups; the multi-node scheduling surface it
    exercises stays covered in tier-1 by tests/test_cluster.py's
    in-process multi-node fixtures. Verified passing before the mark
    (2026-08-05)."""
    head = worker = None
    try:
        head = _spawn_daemon(["start", "--head", "--num-cpus", "2"])
        line, _ = _read_until(head, "controller:")
        addr = line.split()[-1]
        _read_until(head, "to connect:")

        worker = _spawn_daemon(["start", "--address", addr,
                                "--num-cpus", "2",
                                "--resources", '{"spot": 1}'])
        _read_until(worker, "node ")

        host, _, port = addr.partition(":")
        core = ray_tpu.init(address=(host, int(port)))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) == 2:
                break
            time.sleep(0.2)
        assert len(alive) == 2, alive

        @ray_tpu.remote(resources={"spot": 0.1})
        def on_worker_node():
            from ray_tpu.core.runtime import get_core_worker

            return get_core_worker().node_id.hex()

        @ray_tpu.remote
        def anywhere(x):
            return x * 2

        spot_node = ray_tpu.get(on_worker_node.remote(), timeout=60)
        worker_nodes = [n["node_id"] for n in alive
                        if n["resources"].get("spot")]
        assert spot_node in worker_nodes
        assert ray_tpu.get([anywhere.remote(i) for i in range(8)],
                           timeout=60) == [i * 2 for i in range(8)]
    finally:
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        for proc in (worker, head):
            if proc is not None:
                proc.send_signal(signal.SIGTERM)
        for proc in (worker, head):
            if proc is not None:
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()


@pytest.mark.timeout_s(180)
def test_job_cli_submit_and_logs(ray_start_regular):
    from ray_tpu.core import api as api_mod
    from ray_tpu.scripts import main as cli_main

    ctrl = api_mod._local_cluster[0]
    addr = f"{ctrl.address[0]}:{ctrl.address[1]}"
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main(["--address", addr, "job", "submit",
                       f"{sys.executable} -c \"print('job-output-42')\"",
                       "--wait"])
    out = buf.getvalue()
    assert rc == 0, out
    assert "job-output-42" in out
    assert "SUCCEEDED" in out


def test_spillback_rejects_deep_queue(ray_start_cluster):
    """A backlogged node bounces new leases so submitters re-pick; the
    burst still completes by settling into queues on later attempts."""
    import ray_tpu
    from ray_tpu.core.config import config

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    old = config.snapshot()["lease_spillback_queue_depth"]
    config.update({"lease_spillback_queue_depth": 2})
    try:
        @ray_tpu.remote
        def slowish(i):
            time.sleep(0.3)
            from ray_tpu.core.runtime import get_core_worker

            return get_core_worker().node_id.hex()

        # 12 tasks over 2 single-CPU nodes: queues go deep; spillback must
        # not deadlock or fail the burst, and both nodes serve tasks.
        nodes = ray_tpu.get([slowish.remote(i) for i in range(12)],
                            timeout=120)
        assert len(nodes) == 12
        assert len(set(nodes)) == 2, set(nodes)
    finally:
        config.update({"lease_spillback_queue_depth": old})


def test_memory_cli_and_usage_report(ray_start_regular):
    import io
    from contextlib import redirect_stdout

    from ray_tpu import usage
    from ray_tpu.core import api as api_mod
    from ray_tpu.scripts import main as cli_main

    # Put something sizable so store usage is visible.
    ref = ray_tpu.put(np.ones(500_000))
    ctrl = api_mod._local_cluster[0]
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main(["--address",
                       f"{ctrl.address[0]}:{ctrl.address[1]}", "memory"])
    out = buf.getvalue()
    assert rc == 0
    assert "store_used" in out and "MB" in out

    usage.record_feature("test.feature")
    path = usage.write_report()
    assert path
    import json

    report = json.load(open(path))
    assert "test.feature" in report["features"]
    assert report["nodes"] == 1
    del ref
