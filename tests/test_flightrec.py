"""Flight recorder (util/flightrec.py) + doctor post-mortem (ISSUE 15).

Cluster-free contract:

* the ring is bounded (oldest events evicted), always-on recording is
  a deque append, and ``record`` never raises even with an unwritable
  spill dir;
* ``flush_now``/``dump_all`` round-trip events through the per-process
  file (atomic replace; torn/foreign files skipped; ``max_age_s``
  drops stale sessions);
* ``doctor.post_mortem`` is a PURE function over merged dumps: given a
  synthetic crash history it names the first-dying member, the stage
  whose clock stopped, the surviving epoch, and whether the replay
  double-apply guard fired — no cluster, no metrics, evidence only.

The injection-tested halves (a REAL SIGKILLed stage actor / gang
coordinator) live with their module clusters in
tests/test_pipeline_plane.py and tests/test_multihost_group.py.
"""

import json
import os

import pytest

from ray_tpu import doctor
from ray_tpu.core.config import config
from ray_tpu.util import flightrec


@pytest.fixture()
def fr_dir(tmp_path):
    saved_dir = config.flightrec_dir
    saved_ring = config.flightrec_ring
    config.flightrec_dir = str(tmp_path)
    flightrec.reset()
    yield str(tmp_path)
    flightrec.reset()
    config.flightrec_dir = saved_dir
    config.flightrec_ring = saved_ring


def test_ring_is_bounded_and_ordered(fr_dir):
    config.flightrec_ring = 16
    flightrec.reset()
    for i in range(100):
        flightrec.record("t.tick", n=i)
    events = flightrec.dump()
    assert len(events) == 16
    assert [e["n"] for e in events] == list(range(84, 100))
    assert all(e["ev"] == "t.tick" and "ts" in e for e in events)


def test_disabled_recorder_is_a_noop(fr_dir):
    config.flightrec_enabled = False
    try:
        flightrec.reset()
        flightrec.record("t.tick", n=1)
        assert flightrec.dump() == []
    finally:
        config.flightrec_enabled = True


def test_flush_and_dump_all_roundtrip(fr_dir):
    flightrec.record("t.alpha", n=1)
    flightrec.record("t.beta", n=2)
    path = flightrec.flush_now()
    assert path and os.path.exists(path)
    # Torn/foreign files are skipped, not fatal.
    with open(os.path.join(fr_dir, "fr-99999.json"), "w") as f:
        f.write("{not json")
    with open(os.path.join(fr_dir, "unrelated.txt"), "w") as f:
        f.write("hi")
    dumps = flightrec.dump_all(fr_dir)
    assert len(dumps) == 1
    (source, doc), = dumps.items()
    assert doc["pid"] == os.getpid()
    assert [e["ev"] for e in doc["events"]] == ["t.alpha", "t.beta"]
    assert f"pid{os.getpid()}" in source
    # max_age_s drops stale sessions (this one is fresh).
    assert flightrec.dump_all(fr_dir, max_age_s=60.0)
    assert flightrec.dump_all(fr_dir, max_age_s=-1.0) == {}


def test_record_survives_unwritable_dir(fr_dir):
    config.flightrec_dir = "/proc/definitely/not/writable"
    flightrec.record("t.alpha", n=1)
    assert flightrec.flush_now() is None  # refused, not raised
    assert [e["ev"] for e in flightrec.dump()] == ["t.alpha"]


def test_cluster_dump_includes_own_ring(fr_dir):
    flightrec.record("t.alpha", n=1)
    dumps = flightrec.cluster_dump()
    assert any(e["ev"] == "t.alpha"
               for doc in dumps.values() for e in doc["events"])


# ------------------------------------------------------- post-mortem


def _gang_death_dumps(t0=1000.0):
    """A synthetic crash history: host-1 of pipe 'pm' is SIGKILLed by
    a faultinject die rule at its beat site; the monitor reconciles
    the epoch-1 gang and a fresh one forms under epoch 2."""
    return {
        "driver-pid1": {"pid": 1, "role": "driver", "events": [
            {"ev": "gang.register", "ts": t0, "group": "pm-gang",
             "epoch": 1, "hosts": 2},
            {"ev": "gang.form", "ts": t0 + 0.2, "group": "pm-gang",
             "epoch": 1, "hosts": 2},
            {"ev": "pipe.step.start", "ts": t0 + 1.0, "pipeline": "pm",
             "step": 0, "mbs": 4},
            {"ev": "pipe.clock.drift", "ts": t0 + 2.0, "pipeline": "pm",
             "step": 1, "clocks": "2,1"},
            {"ev": "gang.reconcile", "ts": t0 + 3.0, "group": "pm-gang",
             "epoch": 1, "dead": "host-1", "coordinator_died": False},
            {"ev": "gang.register", "ts": t0 + 3.5, "group": "pm-gang",
             "epoch": 2, "hosts": 2},
            {"ev": "gang.form", "ts": t0 + 4.0, "group": "pm-gang",
             "epoch": 2, "hosts": 2},
        ]},
        "worker-pid2": {"pid": 2, "role": "worker", "events": [
            {"ev": "gang.member.up", "ts": t0 + 0.1, "group": "pm-gang",
             "member": "host-0", "epoch": 1},
            {"ev": "pipe.stage.begin", "ts": t0 + 1.1, "pipeline": "pm",
             "stage": 0, "step": 0, "asked": 0},
            {"ev": "pipe.stage.apply", "ts": t0 + 2.5, "pipeline": "pm",
             "stage": 0, "step": 1},
            {"ev": "pipe.stage.begin", "ts": t0 + 6.0, "pipeline": "pm",
             "stage": 0, "step": 1, "asked": 1},
        ]},
        "worker-pid3": {"pid": 3, "role": "worker", "events": [
            {"ev": "gang.member.up", "ts": t0 + 0.1, "group": "pm-gang",
             "member": "host-1", "epoch": 1},
            {"ev": "pipe.stage.begin", "ts": t0 + 1.1, "pipeline": "pm",
             "stage": 1, "step": 0, "asked": 0},
            {"ev": "fault.fired", "ts": t0 + 2.8,
             "site": "multihost.member.pm-gang.host-1.beat",
             "action": "die"},
        ]},
    }


def test_post_mortem_names_first_dying_member_and_surviving_epoch():
    findings = doctor.post_mortem(_gang_death_dumps())
    deaths = [f for f in findings if f["signature"] == "gang-death"]
    assert len(deaths) == 1
    d = deaths[0]
    assert d["evidence"]["first_dying"] == "host-1"
    assert d["evidence"]["surviving_epoch"] == 2
    assert d["evidence"]["injected"] is True
    assert "host-1" in d["summary"]
    assert "epoch 2" in d["summary"]
    # Member <-> stage correlation: host-1 hosts stage s1 of 'pm'.
    assert "s1" in d["summary"]
    assert "SIGKILL" in d["summary"]


def test_post_mortem_names_stopped_stage_clock():
    findings = doctor.post_mortem(_gang_death_dumps())
    stops = [f for f in findings
             if f["signature"] == "stage-clock-stop"]
    assert len(stops) == 1
    s = stops[0]
    # Stage 1's last event is ~3.2s before stage 0 went quiet and its
    # clock never reached step 1.
    assert s["evidence"]["stopped_stages"] == ["s1"]
    assert s["evidence"]["stage_clocks"] == {"s0": 1, "s1": 0}
    assert "s1" in s["summary"]


def test_post_mortem_reports_double_apply_guard_and_faults():
    findings = doctor.post_mortem(_gang_death_dumps())
    guards = [f for f in findings
              if f["signature"] == "double-apply-guard"]
    assert len(guards) == 1
    assert guards[0]["evidence"] == {"step": 1, "clocks": "2,1"}
    assert "double-apply guard FIRED" in guards[0]["summary"]
    faults = [f for f in findings
              if f["signature"] == "fault-injection"]
    assert len(faults) == 1
    assert faults[0]["evidence"]["fires"][0]["action"] == "die"


def test_post_mortem_quiet_on_orderly_history():
    dumps = {"driver-pid1": {"pid": 1, "role": "driver", "events": [
        {"ev": "gang.register", "ts": 1.0, "group": "g", "epoch": 1,
         "hosts": 2},
        {"ev": "pipe.step.commit", "ts": 2.0, "pipeline": "p",
         "step": 0},
        {"ev": "gang.shutdown", "ts": 3.0, "group": "g", "epoch": 1},
    ]}}
    assert doctor.post_mortem(dumps) == []
    text = doctor.render_post_mortem([], dumps)
    assert "no deaths or stalls" in text


def test_post_mortem_render_and_gang_dead_outcome():
    dumps = _gang_death_dumps()
    # No re-formation on record past the reconcile: the budget-
    # exhausted ending instead.
    dumps["driver-pid1"]["events"] = [
        e for e in dumps["driver-pid1"]["events"]
        if not (e["ts"] > 1003.0 and e["ev"] in ("gang.register",
                                                 "gang.form"))
    ] + [{"ev": "gang.dead", "ts": 1003.6, "group": "pm-gang",
          "epoch": 1, "cause": "restart budget exhausted"}]
    findings = doctor.post_mortem(dumps)
    d = [f for f in findings if f["signature"] == "gang-death"][0]
    assert d["evidence"]["surviving_epoch"] is None
    assert "DEAD" in d["summary"]
    text = doctor.render_post_mortem(findings, dumps)
    assert "gang-death" in text and "post-mortem over 3" in text
    json.dumps(findings)  # --json path stays serializable
