"""Shared-memory object store tests (model: reference plasma tests +
``python/ray/tests/test_object_store.py``)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._native.objstore import ShmStore


@pytest.fixture
def store(tmp_path):
    s = ShmStore.create(str(tmp_path / "test.store"), 8 << 20)
    yield s
    s.close()


def test_put_get_roundtrip(store):
    oid = os.urandom(16)
    assert store.put_bytes(oid, b"x" * 1000)
    with store.get_view(oid) as view:
        assert bytes(view.data) == b"x" * 1000


def test_missing_object(store):
    assert store.get_view(os.urandom(16)) is None
    assert not store.contains(os.urandom(16))


def test_duplicate_create_fails(store):
    oid = os.urandom(16)
    assert store.put_bytes(oid, b"a")
    assert not store.put_bytes(oid, b"b")


def test_eviction_under_pressure(store):
    ids = [os.urandom(16) for _ in range(20)]
    for oid in ids:
        assert store.put_bytes(oid, bytes(1 << 20))
    # 20 MB into an 8 MB store: early objects evicted, store stays bounded.
    assert store.used_bytes() <= store.capacity()
    assert not store.contains(ids[0])
    assert store.contains(ids[-1])


def test_pinned_survives_eviction(store):
    pinned = os.urandom(16)
    store.put_bytes(pinned, b"keep me")
    view = store.get_view(pinned)
    for _ in range(20):
        store.put_bytes(os.urandom(16), bytes(1 << 20))
    assert store.contains(pinned)
    view.release()


def test_delete_frees_space(store):
    oid = os.urandom(16)
    store.put_bytes(oid, bytes(1 << 20))
    used = store.used_bytes()
    assert store.delete(oid)
    assert store.used_bytes() < used


def test_oversized_object_rejected(store):
    assert not store.put_bytes(os.urandom(16), bytes(64 << 20))


def test_large_results_cross_node(ray_start_cluster):
    """A large result produced on node A is readable from node B via the
    node object server (reference: ObjectManager pull path)."""
    cluster = ray_start_cluster
    a = cluster.add_node(num_cpus=1, resources={"A": 1})
    b = cluster.add_node(num_cpus=1, resources={"B": 1})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def produce():
        return np.arange(1 << 20, dtype=np.float64)  # 8 MB

    @ray_tpu.remote
    def consume(arr):
        return float(arr[-1])

    ref = produce.options(num_cpus=0, resources={"A": 1}).remote()
    out = ray_tpu.get(
        consume.options(num_cpus=0, resources={"B": 1}).remote(ref))
    assert out == float((1 << 20) - 1)


def test_zero_copy_numpy_view(ray_start_regular):
    """Local gets of shm-resident arrays are zero-copy views of the store."""
    arr = np.ones(1 << 20, dtype=np.float32)  # 4 MB => shm path
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)
    # A zero-copy view is read-only (backed by the store mmap).
    assert not out.flags.writeable
