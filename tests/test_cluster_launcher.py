"""Cluster launcher: YAML schema, command runners, TPU VM API client, and
the end-to-end ``up`` path (VERDICT r2 #3; reference:
``autoscaler/_private/commands.py``, ``gcp/node_provider.py:75-94``,
``tpu_command_runner.py``, ``ray-schema.json``)."""

import json
import time

import pytest

from ray_tpu.cluster_config import ConfigError, validate_config


# ------------------------------------------------------------------ schema


def test_config_defaults_and_validation():
    cfg = validate_config({"cluster_name": "demo"})
    assert cfg.provider.type == "fake_multinode"
    assert cfg.max_workers == 8

    cfg = validate_config({
        "provider": {"type": "tpu_vm", "project_id": "p",
                     "zone": "us-central2-b",
                     "accelerator_type": "v5litepod-16"},
        "worker": {"resources": {"TPU": 16, "CPU": 8},
                   "labels": {"pool": "tpu"}},
        "min_workers": 1, "max_workers": 4,
        "setup_commands": ["echo hi"],
        "dry_run": True,
    })
    assert cfg.provider.zone == "us-central2-b"
    assert cfg.worker.resources == {"TPU": 16.0, "CPU": 8.0}


@pytest.mark.parametrize("raw,frag", [
    ({"bogus_key": 1}, "unknown keys"),
    ({"provider": {"type": "aws"}}, "provider.type"),
    ({"provider": {"type": "tpu_vm", "zone": "z"}}, "project_id"),
    ({"min_workers": 5, "max_workers": 2}, "min_workers"),
    ({"worker": {"resources": {"CPU": -1}}}, "non-negative"),
    ({"setup_commands": "echo"}, "list of strings"),
])
def test_config_rejects_bad_input(raw, frag):
    with pytest.raises(ConfigError, match=frag):
        validate_config(raw)


# --------------------------------------------------------- command runners


def test_ssh_runner_builds_argv_dry_run():
    from ray_tpu.command_runner import SSHCommandRunner, TPUPodCommandRunner

    r = SSHCommandRunner("10.0.0.5", user="ray", key_file="/k.pem",
                         dry_run=True)
    r.run("echo hello")
    argv = r.history[0]
    assert argv[0] == "ssh" and "-i" in argv and "/k.pem" in argv
    assert "ray@10.0.0.5" in argv
    r.put("/tmp/a", "/tmp/b")
    assert r.history[1][0] == "scp"

    pod = TPUPodCommandRunner(["10.0.0.5", "10.0.0.6"], dry_run=True)
    pod.run("start")
    assert len(pod.history) == 2  # fanned out to every slice host
    pod.run_per_host("python -m ray_tpu start",
                     [{"RANK": "0"}, {"RANK": "1"}])
    assert any("RANK=1" in " ".join(argv) for argv in pod.history)


def test_subprocess_runner_executes():
    from ray_tpu.command_runner import CommandFailed, SubprocessCommandRunner

    r = SubprocessCommandRunner()
    assert r.run("echo ok").strip() == "ok"
    with pytest.raises(CommandFailed):
        r.run("exit 3")


# ----------------------------------------------------------- tpu_vm client


def _fake_cloud():
    """In-memory TPU API: nodes keyed by path, ops complete instantly."""
    state = {"nodes": {}, "counter": 0}

    def transport(verb, url, body, headers):
        path = url.split("/v2/", 1)[1]
        if verb == "POST":
            name = path.split("nodeId=")[1]
            node_path = path.split("?")[0] + "/" + name
            state["nodes"][node_path] = {
                "name": node_path, "state": "READY",
                "labels": (body or {}).get("labels", {}),
                "networkEndpoints": [{"ipAddress": f"10.0.0.{len(state['nodes']) + 1}"},
                                     {"ipAddress": f"10.0.1.{len(state['nodes']) + 1}"}],
            }
            return {"name": node_path + "/op", "done": True}
        if verb == "DELETE":
            state["nodes"].pop(path, None)
            return {"name": path + "/del", "done": True}
        if path.endswith("/nodes"):
            return {"nodes": list(state["nodes"].values())}
        return state["nodes"].get(path, {})

    return state, transport


def test_tpu_vm_client_crud_and_hosts():
    from ray_tpu.tpu_vm_api import TpuVmClient

    state, transport = _fake_cloud()
    client = TpuVmClient("proj", "us-central2-b", token_fn=lambda: "tok",
                         transport=transport)
    op = client.create_node("s1", "v5litepod-16", "v2-alpha-tpuv5-lite",
                            labels={"ray-cluster": "demo"})
    client.wait_operation(op)
    nodes = client.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "READY"
    node = client.get_node(nodes[0]["name"])
    assert TpuVmClient.node_hosts(node) == ["10.0.0.1", "10.0.1.1"]
    client.delete_node(nodes[0]["name"])
    assert client.list_nodes() == []
    # Request bodies carried the gang-atomic slice shape.
    post = client.requests[0]
    assert post["body"]["acceleratorType"] == "v5litepod-16"


def test_tpu_vm_provider_slice_gang_bootstrap():
    """Provider creates a slice, waits READY, and hands every slice host to
    the bootstrap hook (the SSH fan-out path)."""
    from ray_tpu.autoscaler import TPUVMNodeProvider
    from ray_tpu.tpu_vm_api import TpuVmClient

    state, transport = _fake_cloud()
    client = TpuVmClient("proj", "us-central2-b", token_fn=lambda: "",
                         transport=transport)
    booted = []
    provider = TPUVMNodeProvider(
        client=client, accelerator_type="v5litepod-16",
        bootstrap=lambda node, labels: booted.append(
            (TpuVmClient.node_hosts(node), labels)))
    pid = provider.create_node({"TPU": 16.0}, {"pool": "tpu"})
    assert pid in provider.non_terminated_nodes()
    hosts, labels = booted[0]
    assert len(hosts) == 2 and labels["provider_node_id"] == pid
    provider.terminate_node(pid)
    assert provider.non_terminated_nodes() == []


# -------------------------------------------------------------- end-to-end


@pytest.mark.slow  # PR 20 rebudget (5.4s): end-to-end launcher soak;
# the autoscaler decision units stay tier-1
@pytest.mark.timeout_s(170)
def test_up_fake_multinode_autoscales_end_to_end(tmp_path):
    """``ray_tpu up`` on a fake_multinode YAML boots a real autoscaling
    cluster: demand appears -> workers launch -> tasks run on them ->
    idle timeout scales back down."""
    import yaml

    import ray_tpu
    from ray_tpu.cluster_launcher import up

    config = tmp_path / "cluster.yaml"
    config.write_text(yaml.safe_dump({
        "cluster_name": "fake-e2e",
        "provider": {"type": "fake_multinode"},
        "min_workers": 0,
        "max_workers": 3,
        "idle_timeout_minutes": 0.05,  # 3s
        "head": {"resources": {"CPU": 0.1}},
        "worker": {"resources": {"CPU": 2}, "labels": {"pool": "w"}},
    }))
    cluster = up(str(config))
    try:
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote
        def where():
            from ray_tpu.core.runtime import get_core_worker

            return get_core_worker().node_id.hex()

        # Head has 0.1 CPU: these need autoscaled workers.
        refs = [where.options(num_cpus=1).remote() for _ in range(6)]
        nodes = ray_tpu.get(refs, timeout=120)
        assert cluster.autoscaler.num_launches >= 1
        head_hex = cluster.head_node.node_id.hex()
        assert all(n != head_hex for n in nodes)

        # Scale-down: workers idle past the (3s) timeout get terminated.
        deadline = time.monotonic() + 60
        while cluster.provider.non_terminated_nodes():
            assert time.monotonic() < deadline, "idle workers never reaped"
            time.sleep(0.5)
        assert cluster.autoscaler.num_terminations >= 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_up_tpu_vm_dry_run_records_provisioning(tmp_path):
    """Dry-run tpu_vm ``up``/``down``: the exact REST requests and SSH argv
    are recorded without egress — head slice create, per-host setup +
    ray-start, teardown delete."""
    import yaml

    from ray_tpu.cluster_launcher import down, up

    config = tmp_path / "tpu.yaml"
    config.write_text(yaml.safe_dump({
        "cluster_name": "pod256",
        "provider": {"type": "tpu_vm", "project_id": "proj",
                     "zone": "us-central2-b",
                     "accelerator_type": "v5litepod-256"},
        "max_workers": 2,
        "worker": {"resources": {"TPU": 256, "CPU": 64}},
        "auth": {"ssh_user": "ray", "ssh_private_key": "/k.pem"},
        "setup_commands": ["pip install -e ."],
        "dry_run": True,
    }))
    cluster = up(str(config))
    try:
        reqs = cluster.provider._client.requests
        post = next(r for r in reqs if r["verb"] == "POST")
        assert post["body"]["acceleratorType"] == "v5litepod-256"
        assert "pod256-head" in post["path"]
        assert any("started head" in a for a in cluster.actions)
    finally:
        cluster.shutdown()
    assert down(str(config))  # records the delete intent


def test_cli_up_down_dry_run(tmp_path, capsys):
    import yaml

    from ray_tpu.scripts import main

    config = tmp_path / "c.yaml"
    config.write_text(yaml.safe_dump({
        "cluster_name": "cli",
        "provider": {"type": "tpu_vm", "project_id": "p", "zone": "z"},
        "dry_run": True,
    }))
    assert main(["up", str(config)]) == 0
    assert "dry run" in capsys.readouterr().out
    assert main(["down", str(config)]) == 0
    assert "cluster down" in capsys.readouterr().out
