"""Autopilot closed-loop remediation (ISSUE 18): hysteresis, token
buckets, epoch fencing (stale evidence must no-op with an audit
record, never double-kill), dry-run, the kill-switch OFF path, the
gang already-remediated guard, MTTR accounting, the doctor's
machine-readable remediation schema, and the severity-aware doctor
exit codes. Everything here runs against injected fakes (client /
serve surface / clock) — the live-cluster path is exercised by
``make bench-chaos``."""

import json

import pytest

from ray_tpu.autopilot import ACTION_CLASSES, Autopilot, TokenBucket
from ray_tpu.core.config import config
from ray_tpu.util.metrics import _Registry, counter_totals


def _agg():
    return {"n1/test/pid1": _Registry.get().snapshot()}


def _counter(name, reason=None, action=None, outcome=None):
    want = {}
    if reason is not None:
        want["reason"] = reason
    if action is not None:
        want["action"] = action
    if outcome is not None:
        want["outcome"] = outcome
    total = 0.0
    for key, val in counter_totals(_agg(), name).items():
        tags = dict(key)
        if all(tags.get(k) == v for k, v in want.items()):
            total += val
    return total


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeClient:
    """Scripted .call transport: records every RPC the autopilot makes
    so tests can assert exactly which control surfaces were touched."""

    def __init__(self):
        self.calls = []
        self.nodes = []
        self.group = None
        self.put_result = {"ok": True, "epoch": 0}

    def call(self, method, *args, **kwargs):
        kwargs.pop("timeout", None)
        self.calls.append((method, args, kwargs))
        if method == "list_nodes":
            return self.nodes
        if method == "mh_group_state":
            return self.group
        if method == "mh_group_put":
            return self.put_result
        if method == "kv_put":
            return True
        if method == "taint_host":
            return {"node": args[0], "ttl_s": 120.0}
        if method == "taint_state":
            return {}
        raise KeyError(method)

    def methods(self):
        return [m for m, _a, _k in self.calls]


class FakeServe:
    def __init__(self, epoch=7):
        self.epoch = epoch
        self.resizes = []
        self.sheds = []
        self.deployments = {"llama": {"load": 20.0, "replicas": 1}}

    def status(self):
        return self.deployments

    def autopilot_resize(self, deployment, delta, epoch):
        if int(epoch) != self.epoch:
            return {"ok": False, "reason": "stale-epoch"}
        self.resizes.append((deployment, delta))
        return {"ok": True, "target": 2, "epoch": epoch}

    def autopilot_shed(self, deployment, queue_max, epoch):
        if int(epoch) != self.epoch:
            return {"ok": False, "reason": "stale-epoch"}
        self.sheds.append((deployment, queue_max))
        return {"ok": True, "queue_max": queue_max, "replicas": 1,
                "epoch": epoch}


def slo_finding(dep="llama"):
    return {"signature": "slo-burn", "severity": "warning",
            "source": f"deployment:{dep}",
            "summary": "p99 over objective", "evidence": {"p99_s": 9.0},
            "remediation": {"action": "resize-deployment", "target": dep,
                            "evidence_keys": ["p99_s"]},
            "remedy": "add replicas"}


def rtt_finding(prefix="aabbccdd"):
    return {"signature": "heartbeat-rtt-outlier", "severity": "warning",
            "source": f"node:{prefix}", "summary": "rtt outlier",
            "evidence": {"node_p99_s": 1.0},
            "remediation": {"action": "taint-host", "target": prefix,
                            "evidence_keys": ["node_p99_s"]},
            "remedy": "drain the host"}


def gang_finding(group="g1", victim="host-1", old_epoch=3):
    return {"signature": "gang-death", "severity": "critical",
            "source": f"group:{group}", "summary": "member died",
            "evidence": {"first_dying": victim, "old_epoch": old_epoch},
            "remediation": {"action": "reschedule-gang", "target": group,
                            "evidence_keys": ["first_dying"]},
            "remedy": "check the host"}


def make_pilot(monkeypatch, clock=None, serve=None, client=None,
               enabled=True, dry_run=False, burst=2, rate=2.0):
    monkeypatch.setattr(config, "autopilot_enabled", enabled)
    monkeypatch.setattr(config, "autopilot_dry_run", dry_run)
    monkeypatch.setattr(config, "autopilot_burst", burst)
    monkeypatch.setattr(config, "autopilot_rate_per_min", rate)
    return Autopilot(client=client or FakeClient(),
                     serve=serve or FakeServe(),
                     clock=clock or FakeClock())


# ------------------------------------------------------------ hysteresis


def test_single_window_takes_no_action(monkeypatch):
    """A signature seen in ONE doctor window must not trigger anything
    (hysteresis >= 2 windows): transient blips are not incidents."""
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, serve=serve)
    before = _counter("autopilot_suppressed_total", reason="hysteresis")
    records = pilot.step([slo_finding()], serve_epoch=7)
    assert records == []
    assert serve.resizes == []
    assert _counter("autopilot_suppressed_total",
                    reason="hysteresis") == before + 1
    # Second consecutive window: the damper opens and the action fires.
    records = pilot.step([slo_finding()], serve_epoch=7)
    assert [r["outcome"] for r in records] == ["applied"]
    assert serve.resizes == [("llama", 1)]


def test_signature_gap_resets_streak(monkeypatch):
    """Present, absent, present again = two one-window blips, not a
    two-window streak — no action fires."""
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, serve=serve)
    pilot.step([slo_finding()], serve_epoch=7)
    pilot.step([], serve_epoch=7)
    records = pilot.step([slo_finding()], serve_epoch=7)
    assert records == [] and serve.resizes == []


# ------------------------------------------------------------ rate limit


def test_rate_limit_exhaustion_suppresses_with_metric(monkeypatch):
    """Burst of 1: the second same-class action in a window is
    suppressed and counted — remediation storms must degrade to
    alerts, not cascade."""
    serve = FakeServe()
    serve.deployments["gpt"] = {"load": 12.0, "replicas": 1}
    pilot = make_pilot(monkeypatch, serve=serve, burst=1, rate=0.0)
    two = [slo_finding("llama"), slo_finding("gpt")]
    pilot.step(two, serve_epoch=7)
    before = _counter("autopilot_suppressed_total", reason="rate-limit")
    records = pilot.step(two, serve_epoch=7)
    assert [r["outcome"] for r in records] == ["applied"]
    assert len(serve.resizes) == 1
    assert _counter("autopilot_suppressed_total",
                    reason="rate-limit") == before + 1


def test_token_bucket_refills_on_injected_clock():
    clock = FakeClock()
    bucket = TokenBucket(rate_per_min=60.0, burst=2, clock=clock)
    assert bucket.take() and bucket.take() and not bucket.take()
    clock.advance(1.0)  # 60/min == 1 token/s
    assert bucket.take() and not bucket.take()


# ------------------------------------------------------------ fencing


def test_stale_serve_epoch_noops_with_audit(monkeypatch):
    """Evidence observed against serve epoch 3; the controller is at 7
    (it restarted since) — the action must no-op AND leave an audit
    record naming the refusal."""
    serve = FakeServe(epoch=7)
    pilot = make_pilot(monkeypatch, serve=serve)
    pilot.step([slo_finding()], serve_epoch=3)
    records = pilot.step([slo_finding()], serve_epoch=3)
    assert [r["outcome"] for r in records] == ["stale-epoch"]
    assert serve.resizes == []
    audit = pilot.status()["audit"]
    assert audit and audit[-1]["signature"] == "slo-burn"
    assert audit[-1]["action"] == "resize-deployment"
    assert audit[-1]["outcome"] == "stale-epoch"


def test_stale_gang_epoch_never_double_kills(monkeypatch):
    """The group registry refuses the eviction write (the gang already
    re-registered under a newer epoch == it self-healed): outcome is
    stale-epoch, audited, and no second eviction is attempted."""
    client = FakeClient()
    client.group = {"group_id": "g1", "epoch": 4,
                    "members": {"host-0": {}, "host-1": {}}}
    client.put_result = {"ok": False, "reason": "stale_epoch", "epoch": 5}
    pilot = make_pilot(monkeypatch, client=client)
    pilot.step([gang_finding()])
    records = pilot.step([gang_finding()])
    assert [r["outcome"] for r in records] == ["stale-epoch"]
    assert records[0]["reason"] == "stale_epoch"
    # The fenced write was attempted exactly once and refused.
    assert client.methods().count("mh_group_put") == 1
    assert pilot._gang_acted == {}


def test_gang_already_remediated_guard(monkeypatch):
    """After the autopilot evicts at epoch E, its OWN eviction shows up
    as a fresh gang-death next pass — the acted-epoch guard must stop
    the loop; a genuinely new death (epoch > E) acts again."""
    client = FakeClient()
    client.group = {"group_id": "g1", "epoch": 4,
                    "members": {"host-0": {}, "host-1": {}}}
    client.put_result = {"ok": True, "epoch": 4}
    pilot = make_pilot(monkeypatch, client=client, burst=8)
    pilot.step([gang_finding()])
    records = pilot.step([gang_finding()])
    assert [r["outcome"] for r in records] == ["applied"]
    assert records[0]["detail"]["victim"] == "host-1"
    assert pilot._gang_acted == {"g1": 4}
    # Same epoch re-observed (our own reconcile's echo): no-op.
    pilot.step([gang_finding()])
    records = pilot.step([gang_finding()])
    assert [r["outcome"] for r in records] == ["stale-epoch"]
    assert records[0]["reason"] == "already-remediated"
    assert client.methods().count("mh_group_put") == 1
    # The gang died AGAIN after re-forming (epoch moved on): act. The
    # streak is already past the damper (the stale-epoch dispatch does
    # not re-arm it), so the very next window acts.
    client.group = {"group_id": "g1", "epoch": 6,
                    "members": {"host-0": {}, "host-1": {}}}
    records = pilot.step([gang_finding(old_epoch=5)])
    assert [r["outcome"] for r in records] == ["applied"]
    assert client.methods().count("mh_group_put") == 2


def test_taint_fence_requires_live_node(monkeypatch):
    """The RTT evidence names a node by metric-label prefix; if no
    LIVE node resolves it (died / replaced since diagnosis), the taint
    must no-op as stale."""
    client = FakeClient()
    client.nodes = [{"node_id": "aabbccdd" + "0" * 56, "alive": False}]
    pilot = make_pilot(monkeypatch, client=client)
    pilot.step([rtt_finding()])
    records = pilot.step([rtt_finding()])
    assert [r["outcome"] for r in records] == ["stale-epoch"]
    assert "taint_host" not in client.methods()


def test_taint_applies_to_resolved_live_node(monkeypatch):
    client = FakeClient()
    full = "aabbccdd" + "0" * 56
    client.nodes = [{"node_id": full, "alive": True}]
    pilot = make_pilot(monkeypatch, client=client)
    pilot.step([rtt_finding()])
    records = pilot.step([rtt_finding()])
    assert [r["outcome"] for r in records] == ["applied"]
    assert records[0]["target"] == full
    assert "taint_host" in client.methods()
    assert records[0]["mttr_s"] >= 0.0


# ------------------------------------------------------------- dry run


def test_dry_run_takes_zero_actions(monkeypatch):
    """--dry-run evaluates fences and reports what WOULD fire but
    mutates nothing anywhere."""
    client = FakeClient()
    client.nodes = [{"node_id": "aabbccdd" + "0" * 56, "alive": True}]
    client.group = {"group_id": "g1", "epoch": 4,
                    "members": {"host-0": {}, "host-1": {}}}
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, client=client, serve=serve,
                       dry_run=True, burst=8)
    findings = [slo_finding(), rtt_finding(), gang_finding()]
    pilot.step(findings, serve_epoch=7)
    records = pilot.step(findings, serve_epoch=7)
    assert sorted(r["outcome"] for r in records) == ["dry-run"] * 3
    for mutator in ("taint_host", "mh_group_put", "kv_put"):
        assert mutator not in client.methods()
    assert serve.resizes == [] and serve.sheds == []


# --------------------------------------------------------- kill switch


def test_kill_switch_off_touches_nothing(monkeypatch):
    """autopilot_enabled=False (the default): no fence probe, no RPC,
    no serve call — indistinguishable from no autopilot at all."""
    from ray_tpu.core.config import _FLAG_DEFS

    assert _FLAG_DEFS["autopilot_enabled"][1] is False
    client = FakeClient()
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, client=client, serve=serve,
                       enabled=False)
    before = _counter("autopilot_suppressed_total", reason="disabled")
    for _ in range(3):
        records = pilot.step(
            [slo_finding(), rtt_finding(), gang_finding()],
            serve_epoch=7)
        assert records == []
    assert client.calls == []
    assert serve.resizes == [] and serve.sheds == []
    assert _counter("autopilot_suppressed_total",
                    reason="disabled") == before + 9


# ------------------------------------------------- applied bookkeeping


def test_applied_action_records_mttr_and_rearms(monkeypatch):
    """Applied: MTTR = first-seen -> applied on the injected clock, the
    gauge is set, and the streak re-arms so the SAME streak cannot
    refire next window while the cluster converges."""
    from ray_tpu.util.metrics import gauge_totals

    clock = FakeClock(100.0)
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, serve=serve, clock=clock)
    pilot.step([slo_finding()], serve_epoch=7)
    clock.advance(5.0)
    records = pilot.step([slo_finding()], serve_epoch=7)
    assert records[0]["outcome"] == "applied"
    assert records[0]["mttr_s"] == pytest.approx(5.0)
    mttr = {dict(k).get("action"): v for k, v in
            gauge_totals(_agg(), "autopilot_mttr_s").items()}
    assert mttr.get("resize-deployment") == pytest.approx(5.0)
    # Next window: streak restarted at 1 -> hysteresis suppresses.
    assert pilot.step([slo_finding()], serve_epoch=7) == []
    assert len(serve.resizes) == 1


def test_shed_resolves_tenant_and_halves_queue(monkeypatch):
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, serve=serve)
    finding = {"signature": "rpc-backpressure", "severity": "critical",
               "source": "n1/serve_proxy/pid9", "summary": "queue",
               "evidence": {"queued_bytes": 1 << 26},
               "remediation": {"action": "shed-tenant",
                               "target": "n1/serve_proxy/pid9",
                               "evidence_keys": ["queued_bytes"]},
               "remedy": "shed"}
    pilot.step([finding], serve_epoch=7)
    records = pilot.step([finding], serve_epoch=7)
    assert [r["outcome"] for r in records] == ["applied"]
    # Process key resolved to the busiest deployment; cap = load // 2.
    assert serve.sheds == [("llama", 10)]


def test_actions_counter_labels(monkeypatch):
    before = _counter("autopilot_actions_total",
                      action="resize-deployment", outcome="applied")
    serve = FakeServe()
    pilot = make_pilot(monkeypatch, serve=serve)
    pilot.step([slo_finding()], serve_epoch=7)
    pilot.step([slo_finding()], serve_epoch=7)
    assert _counter("autopilot_actions_total",
                    action="resize-deployment",
                    outcome="applied") == before + 1


# ------------------------------------------- remediation hint schema


def test_remediation_schema_is_pinned():
    """Every doctor finding carries the machine-readable remediation
    contract the autopilot executes against: {action, target,
    evidence_keys} with action in REMEDIATION_ACTIONS or None, and
    evidence_keys sorted + a subset of the evidence dict. JSON
    round-trip stable (the --json consumers parse this)."""
    from ray_tpu import doctor

    assert doctor.REMEDIATION_ACTIONS == tuple(ACTION_CLASSES)

    buckets = (0.0005, 0.001, 0.005, 0.01, 0.1, 0.5, 1.0)

    def rtt(node, fast, slow):
        counts = [0, fast, 0, 0, 0, slow, 0, 0]
        return {"name": "node_heartbeat_rtt_s", "kind": "histogram",
                "tags": {"node": node}, "buckets": list(buckets),
                "counts": counts, "sum": 0.001 * fast + 1.0 * slow,
                "count": fast + slow}

    before = {f"n{i}/node/pid{i}": [rtt(f"n{i}", 0, 0)]
              for i in range(4)}
    after = {f"n{i}/node/pid{i}": [rtt(f"n{i}", 10, 0)]
             for i in range(3)}
    after["n3/node/pid3"] = [rtt("n3", 0, 10)]
    findings = doctor.diagnose(before, after, 2.0)
    assert findings
    for f in json.loads(json.dumps(findings, default=str)):
        rem = f["remediation"]
        assert set(rem) == {"action", "target", "evidence_keys"}
        assert rem["action"] is None \
            or rem["action"] in doctor.REMEDIATION_ACTIONS
        assert rem["evidence_keys"] == sorted(rem["evidence_keys"])
        assert set(rem["evidence_keys"]) <= set(f["evidence"])
    out = [f for f in findings
           if f["signature"] == "heartbeat-rtt-outlier"]
    assert out and out[0]["remediation"]["action"] == "taint-host"
    assert out[0]["remediation"]["target"] == "n3"


def test_slo_burn_finding_carries_resize_hint():
    from ray_tpu import doctor

    hist = {"name": "serve_http_request_s", "kind": "histogram",
            "tags": {"deployment": "llama"},
            "buckets": [0.1, 1.0, 10.0],
            "counts": [0, 0, 20, 0], "sum": 160.0, "count": 20}
    before = {"n1/proxy/p1": [dict(hist, counts=[0, 0, 0, 0],
                                   sum=0.0, count=0)]}
    after = {"n1/proxy/p1": [hist]}
    findings = doctor.diagnose(before, after, 2.0)
    out = [f for f in findings if f["signature"] == "slo-burn"]
    assert out and out[0]["source"] == "deployment:llama"
    rem = out[0]["remediation"]
    assert rem["action"] == "resize-deployment"
    assert rem["target"] == "llama"


# ------------------------------------------------- doctor exit codes


def test_doctor_exit_codes_distinguish_severity():
    from ray_tpu.scripts import _findings_exit_code

    crit = [{"severity": "critical"}]
    warn = [{"severity": "warning"}]
    assert _findings_exit_code([], True) == 0
    assert _findings_exit_code(warn, True) == 1
    assert _findings_exit_code(crit, True) == 2
    assert _findings_exit_code(warn + crit, True) == 2
    assert _findings_exit_code(crit, False) == 0
