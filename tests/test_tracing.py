"""Tracing tests: span propagation through tasks/actors, profile events,
stack dumps (reference: util/tracing/tracing_helper.py + profile_event +
py-spy reporter)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import tracing


def _events(core, match):
    core._flush_task_events()
    events = core.controller.call("list_task_events", 10000)
    return [e for e in events if match(e)]


def test_span_propagates_through_task(ray_start_regular):
    core = ray_start_regular

    @ray_tpu.remote
    def traced_task():
        ctx = tracing.current()
        with tracing.profile_event("inner-work"):
            time.sleep(0.01)
        return ctx

    with tracing.trace("root-span") as (trace_id, _span):
        inside = ray_tpu.get(traced_task.remote())

    # The worker saw the caller's trace id with a fresh span id.
    assert inside is not None and inside[0] == trace_id

    # The task's FINISHED event carries the trace id; the root span and the
    # WORKER-side profile event (flushed on the worker's own cadence) land
    # in the controller's event table too.
    deadline = time.monotonic() + 30
    linked, names = [], set()
    while time.monotonic() < deadline:
        linked = _events(core, lambda e: e.get("trace_id") == trace_id
                         and e.get("state") in ("FINISHED", "FAILED"))
        names = {e["desc"] for e in _events(
            core, lambda e: e.get("state") == "SPAN"
            and e.get("trace_id") == trace_id)}
        if linked and {"root-span", "profile:inner-work"} <= names:
            break
        time.sleep(0.2)
    assert linked, "no task event linked to the trace"
    assert "root-span" in names
    assert "profile:inner-work" in names, names


def test_span_propagates_through_actor(ray_start_regular):
    @ray_tpu.remote
    class Echo:
        def ctx(self):
            return tracing.current()

    actor = Echo.remote()
    with tracing.trace("actor-root") as (trace_id, _):
        inside = ray_tpu.get(actor.ctx.remote())
    assert inside is not None and inside[0] == trace_id
    ray_tpu.kill(actor)


def test_dump_stacks_local():
    text = tracing.dump_stacks()
    assert "thread" in text and "test_dump_stacks_local" in text


def test_worker_stack_dump_rpc(ray_start_regular):
    from ray_tpu.core import api as api_mod
    from ray_tpu.core.rpc import RpcClient

    @ray_tpu.remote
    def napper():
        time.sleep(5)
        return 1

    ref = napper.remote()
    node = api_mod._local_cluster[1]
    deadline = time.monotonic() + 30
    dump = ""
    while time.monotonic() < deadline:
        busy = [w for w in node.list_workers() if not w["idle"]]
        for w in busy:
            try:
                wc = RpcClient(tuple(w["addr"]))
                dump = wc.call("dump_stacks", timeout=10.0)
                wc.close()
            except Exception:
                continue
            if "napper" in dump:
                break
        if "napper" in dump:
            break
        time.sleep(0.2)
    assert "napper" in dump, dump[-2000:]
    assert ray_tpu.get(ref, timeout=60) == 1
