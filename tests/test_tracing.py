"""Tracing tests: span propagation through tasks/actors, profile events,
stack dumps (reference: util/tracing/tracing_helper.py + profile_event +
py-spy reporter)."""

import time

import pytest

import ray_tpu
from ray_tpu.util import tracing


def _events(core, match):
    core._flush_task_events()
    events = core.controller.call("list_task_events", 10000)
    return [e for e in events if match(e)]


def test_span_propagates_through_task(ray_start_regular):
    core = ray_start_regular

    @ray_tpu.remote
    def traced_task():
        ctx = tracing.current()
        with tracing.profile_event("inner-work"):
            time.sleep(0.01)
        return ctx

    with tracing.trace("root-span") as (trace_id, _span):
        inside = ray_tpu.get(traced_task.remote())

    # The worker saw the caller's trace id with a fresh span id.
    assert inside is not None and inside[0] == trace_id

    # The task's FINISHED event carries the trace id; the root span and the
    # WORKER-side profile event (flushed on the worker's own cadence) land
    # in the controller's event table too.
    deadline = time.monotonic() + 30
    linked, names = [], set()
    while time.monotonic() < deadline:
        linked = _events(core, lambda e: e.get("trace_id") == trace_id
                         and e.get("state") in ("FINISHED", "FAILED"))
        names = {e["desc"] for e in _events(
            core, lambda e: e.get("state") == "SPAN"
            and e.get("trace_id") == trace_id)}
        if linked and {"root-span", "profile:inner-work"} <= names:
            break
        time.sleep(0.2)
    assert linked, "no task event linked to the trace"
    assert "root-span" in names
    assert "profile:inner-work" in names, names


def test_span_propagates_through_actor(ray_start_regular):
    @ray_tpu.remote
    class Echo:
        def ctx(self):
            return tracing.current()

    actor = Echo.remote()
    with tracing.trace("actor-root") as (trace_id, _):
        inside = ray_tpu.get(actor.ctx.remote())
    assert inside is not None and inside[0] == trace_id
    ray_tpu.kill(actor)


def test_dump_stacks_local():
    text = tracing.dump_stacks()
    assert "thread" in text and "test_dump_stacks_local" in text


@pytest.mark.slow  # PR 20 rebudget (5.1s): remote stack-dump
# surface; local dump coverage stays tier-1
def test_worker_stack_dump_rpc(ray_start_regular):
    from ray_tpu.core import api as api_mod
    from ray_tpu.core.rpc import RpcClient

    @ray_tpu.remote
    def napper():
        time.sleep(5)
        return 1

    ref = napper.remote()
    node = api_mod._local_cluster[1]
    deadline = time.monotonic() + 30
    dump = ""
    while time.monotonic() < deadline:
        busy = [w for w in node.list_workers() if not w["idle"]]
        for w in busy:
            try:
                wc = RpcClient(tuple(w["addr"]))
                dump = wc.call("dump_stacks", timeout=10.0)
                wc.close()
            except Exception:
                continue
            if "napper" in dump:
                break
        if "napper" in dump:
            break
        time.sleep(0.2)
    assert "napper" in dump, dump[-2000:]
    assert ray_tpu.get(ref, timeout=60) == 1


# ------------------------------------------------- on-demand profiling
# (VERDICT r3 #7; reference: dashboard reporter attaching py-spy/memray
# to live workers, profile_manager.py:79,190)


def test_profile_cpu_flamegraph_of_live_worker(ray_start_regular):
    """Sample a busy worker's stacks over RPC and render a flamegraph:
    the hot function must dominate the samples and appear in the SVG."""
    import ray_tpu
    from ray_tpu.core.rpc import RpcClient
    from ray_tpu.core.runtime import get_core_worker
    from ray_tpu.util.profiling import flamegraph_svg

    @ray_tpu.remote
    def burn(seconds):
        import time as _t

        def hot_loop(deadline):
            x = 0
            while _t.monotonic() < deadline:
                x += 1
            return x

        return hot_loop(_t.monotonic() + seconds)

    ref = burn.remote(4.0)
    time.sleep(0.5)  # let the task start
    core = get_core_worker()
    nodes = core.controller.call("list_nodes")
    workers = []
    for n in nodes:
        nc = RpcClient(tuple(n["addr"]))
        workers += nc.call("list_workers")
        nc.close()
    busy = [w for w in workers if not w["idle"]]
    assert busy, workers
    wc = RpcClient(tuple(busy[0]["addr"]))
    folded = wc.call("profile_cpu", 1.5, 100.0, timeout=30.0)
    wc.close()
    assert sum(folded.values()) > 50  # ~100Hz x 1.5s, load-tolerant
    hot = [s for s in folded if "hot_loop" in s]
    assert hot, list(folded)[:5]
    # Wall-clock sampling counts IDLE threads too (the worker runs ~8
    # service threads parked in waits, like py-spy's all-thread view),
    # and under CI load the busy worker shares one core with the whole
    # suite — so the bar is "clearly present", not a share threshold.
    assert sum(folded[s] for s in hot) >= 10
    svg = flamegraph_svg(folded)
    assert svg.startswith("<svg") and "hot_loop" in svg
    assert ray_tpu.get(ref, timeout=60) > 0


def test_profile_heap_growth(ray_start_regular):
    """Heap profiling over RPC: first call arms tracemalloc, later calls
    report the allocations made in between."""
    import ray_tpu
    from ray_tpu.core.actor import ActorHandle  # noqa: F401
    from ray_tpu.core.rpc import RpcClient

    @ray_tpu.remote
    class Hoarder:
        def __init__(self):
            self.stuff = []

        def grab(self, n):
            self.stuff.append(bytearray(n))
            return len(self.stuff)

        def addr(self):
            from ray_tpu.core.runtime import get_core_worker

            return get_core_worker().addr

    h = Hoarder.remote()
    addr = ray_tpu.get(h.addr.remote(), timeout=60)
    wc = RpcClient(tuple(addr))
    first = wc.call("profile_heap", 10, timeout=30.0)
    assert first["started"] is True
    ray_tpu.get([h.grab.remote(512 * 1024) for _ in range(4)], timeout=60)
    second = wc.call("profile_heap", 10, timeout=30.0)
    wc.close()
    assert second["started"] is False
    assert second["traced_current_kb"] > 1500  # the 4 x 512KB grabs
    assert second["top"], second


def test_profile_heap_stop(ray_start_regular):
    """Heap tracing can be turned back off (a diagnostic probe must not
    slow the worker forever)."""
    import ray_tpu
    from ray_tpu.core.rpc import RpcClient

    @ray_tpu.remote
    class A:
        def addr(self):
            from ray_tpu.core.runtime import get_core_worker

            return get_core_worker().addr

    a = A.remote()
    addr = ray_tpu.get(a.addr.remote(), timeout=60)
    wc = RpcClient(tuple(addr))
    assert wc.call("profile_heap", 5, timeout=30.0)["started"]
    assert wc.call("profile_heap_stop", timeout=30.0)["stopped"]
    # Off again: a new call re-arms rather than snapshotting.
    assert wc.call("profile_heap", 5, timeout=30.0)["started"]
    assert wc.call("profile_heap_stop", timeout=30.0)["stopped"]
    wc.close()
