"""Actor tests (model: reference ``python/ray/tests/test_actor.py``)."""

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.value = start

    def increment(self, by=1):
        self.value += by
        return self.value

    def get(self):
        return self.value


@ray_tpu.remote
class Failing:
    def __init__(self, fail_init=False):
        if fail_init:
            raise RuntimeError("init failed")

    def boom(self):
        raise RuntimeError("method failed")

    def die(self):
        import os

        os._exit(1)


@ray_tpu.remote
class AsyncActor:
    async def double(self, x):
        import asyncio

        await asyncio.sleep(0.01)
        return 2 * x


def test_actor_basic(ray_start_regular):
    counter = Counter.remote(10)
    assert ray_tpu.get(counter.increment.remote()) == 11
    assert ray_tpu.get(counter.increment.remote(5)) == 16
    assert ray_tpu.get(counter.get.remote()) == 16


def test_actor_ordering(ray_start_regular):
    counter = Counter.remote()
    refs = [counter.increment.remote() for _ in range(20)]
    # In-order execution => strictly increasing results.
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_actor_method_error(ray_start_regular):
    actor = Failing.remote()
    with pytest.raises(ray_tpu.TaskError, match="method failed"):
        ray_tpu.get(actor.boom.remote())
    # Actor survives method errors; a second call still reaches it.
    with pytest.raises(ray_tpu.TaskError, match="method failed"):
        ray_tpu.get(actor.boom.remote())


def test_actor_init_error(ray_start_regular):
    actor = Failing.remote(fail_init=True)
    with pytest.raises(ray_tpu.ActorDiedError, match="init failed"):
        ray_tpu.get(actor.boom.remote())


def test_subclass_actor_exports_subclass(ray_start_regular):
    """Regression (PR 10): spawning a BASE actor class must not poison a
    later SUBCLASS spawn. export_callable cached the pickled (key, blob)
    as a class attribute and read it back with getattr — which walks the
    MRO, so the subclass inherited the base's cached export and the
    worker silently instantiated the BASE class with the subclass's
    arguments (how RolloutActor spawns turned into EnvRunner.__init__
    "multiple values for 'num_envs'" whenever classic RL tests ran
    first)."""
    class Base:
        def __init__(self, x=1):
            self.x = x

        def who(self):
            return type(self).__name__

    class Sub(Base):
        def __init__(self, name, x=2):
            super().__init__(x=x)
            self.name = name

        def tag(self):
            return (self.name, self.x, self.who())

    base = ray_tpu.remote(Base).remote()
    assert ray_tpu.get(base.who.remote()) == "Base"
    # Pre-fix this spawned a Base on the worker and died in __init__.
    sub = ray_tpu.remote(Sub).remote("s", x=5)
    assert ray_tpu.get(sub.tag.remote()) == ("s", 5, "Sub")


def test_actor_death_detected(ray_start_regular):
    actor = Failing.remote()
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(actor.boom.remote(), timeout=30)  # actor is up
    actor.die.remote()
    with pytest.raises((ray_tpu.ActorDiedError, ray_tpu.TaskError)):
        ray_tpu.get(actor.boom.remote(), timeout=30)


def test_named_actor(ray_start_regular):
    Counter.options(name="global_counter").remote(100)
    handle = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(handle.get.remote()) == 100


def test_kill_actor(ray_start_regular):
    counter = Counter.remote()
    assert ray_tpu.get(counter.get.remote()) == 0
    ray_tpu.kill(counter)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(counter.get.remote())


def test_actor_handle_passing(ray_start_regular):
    counter = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.increment.remote())

    assert ray_tpu.get(bump.remote(counter)) == 1
    assert ray_tpu.get(counter.get.remote()) == 1


def test_async_actor(ray_start_regular):
    actor = AsyncActor.remote()
    refs = [actor.double.remote(i) for i in range(5)]
    assert ray_tpu.get(refs) == [0, 2, 4, 6, 8]


def test_max_concurrency(ray_start_regular):
    import time

    @ray_tpu.remote
    class Sleeper:
        def nap(self):
            time.sleep(0.5)
            return 1

    actor = Sleeper.options(max_concurrency=4).remote()
    ray_tpu.get(actor.nap.remote())  # warm-up: actor worker fork + import
    start = time.monotonic()
    ray_tpu.get([actor.nap.remote() for _ in range(4)])
    elapsed = time.monotonic() - start
    assert elapsed < 1.5, f"concurrent naps took {elapsed}s (not concurrent)"


def test_actor_restart(ray_start_regular):
    import time

    @ray_tpu.remote
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def bump(self):
            self.calls += 1
            return self.calls

        def die(self):
            import os

            os._exit(1)

    actor = Phoenix.options(max_restarts=1).remote()
    assert ray_tpu.get(actor.bump.remote()) == 1
    actor.die.remote()
    # The lost call errors, then the restarted incarnation serves fresh state.
    deadline = time.monotonic() + 60
    value = None
    while time.monotonic() < deadline:
        try:
            value = ray_tpu.get(actor.bump.remote(), timeout=30)
            break
        except (ray_tpu.ActorDiedError, ray_tpu.TaskError, Exception):
            time.sleep(0.5)
    assert value == 1, f"restarted actor state should reset, got {value}"
    # Second death exhausts max_restarts=1 -> permanently dead.
    actor.die.remote()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(actor.bump.remote(), timeout=30)
            time.sleep(0.5)
        except Exception:
            break
    with pytest.raises((ray_tpu.ActorDiedError, Exception)):
        ray_tpu.get(actor.bump.remote(), timeout=30)
