"""Prefix KV cache: trie match/insert/evict + refcount-vs-evict on the
host index (``serve/prefix_cache.py``), suffix-only prefill equivalence
vs full prefill (``llama_decode.prefill_suffix``), the decode engine's
splice + suffix-prefill admission path, and prefix-affinity routing.
All CPU, tiny configs — tier-1 safe."""

import threading

import numpy as np
import pytest

from ray_tpu.serve.prefix_cache import (PrefixCache, bucket_lengths,
                                        candidate_hashes, prefix_hash)


def _tiny():
    import jax

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, mlp_dim=64, max_seq_len=128)
    params = llama.init_params(cfg, jax.random.key(0))
    return cfg, params


# ------------------------------------------------------------ host index


def test_bucket_lengths_grid():
    assert bucket_lengths(100, 16) == [64, 32, 16]
    assert bucket_lengths(64, 16) == [64, 32, 16]
    assert bucket_lengths(15, 16) == []
    assert bucket_lengths(100, 16, cap=32) == [32, 16]


def test_match_insert_dedup():
    pc = PrefixCache(entries=4, capacity=32, min_tokens=4)
    toks = list(range(10, 30))  # 20 tokens
    assert pc.match(toks) is None
    row, ins_len = pc.insert(toks)
    assert ins_len == 16  # largest power of two <= 20
    assert pc.insert(toks) is None  # dedup on the token key
    m = pc.match(toks)
    assert m == (row, 16)
    pc.release(row)
    assert pc.stats()["hit_rate"] == 0.5  # 1 hit / 2 queries


def test_partial_match_and_min_tokens():
    pc = PrefixCache(4, 32, min_tokens=4)
    toks = list(range(100, 132))
    pc.insert(toks)  # 32-token entry
    # A request sharing only the first 7 tokens still matches (the
    # splice + suffix overwrite makes partial donors correct).
    m = pc.match(toks[:7] + [999] * 20)
    assert m is not None and m[1] == 7
    pc.release(m[0])
    # Below min_tokens: no hit.
    assert pc.match(toks[:3] + [5, 6, 7, 8]) is None


def test_match_leaves_one_suffix_token():
    pc = PrefixCache(4, 32, min_tokens=4)
    toks = list(range(8))
    pc.insert(toks)
    m = pc.match(toks)  # identical prompt: next-token logits still need
    assert m is not None and m[1] == 7  # >= 1 real suffix token
    pc.release(m[0])


def test_nested_entries():
    pc = PrefixCache(4, 32, min_tokens=2)
    long = list(range(16))
    r_long, _ = pc.insert(long)
    short = pc.insert(long[:8])  # strict prefix of an existing entry
    assert short is not None and short[1] == 8
    assert len(pc) == 2


def test_lru_eviction_prunes_trie():
    pc = PrefixCache(2, 32, min_tokens=2)
    a, b, c = [1] * 4, [2] * 4, [3] * 4
    pc.insert(a)
    row_b, _ = pc.insert(b)
    m = pc.match(a + [9])  # touch a: b becomes LRU
    pc.release(m[0])
    row_c, _ = pc.insert(c)
    assert row_c == row_b  # b's row recycled
    assert pc.evictions == 1
    assert pc.match(b + [9]) is None  # b's trie path pruned
    assert pc.match(a + [9]) is not None


def test_refcount_blocks_eviction():
    """The refcount-vs-evict race: a row pinned by an in-flight splice
    must never be recycled, even when it is the LRU victim."""
    pc = PrefixCache(1, 32, min_tokens=2)
    row, _ = pc.insert([1] * 4)
    m = pc.match([1, 1, 1, 1, 9])  # acquires the only row
    assert m is not None
    assert pc.insert([2] * 4) is None  # every row pinned: insert refused
    pc.release(m[0])
    replacement = pc.insert([2] * 4)
    assert replacement is not None and replacement[0] == row


def test_candidate_hashes_match_advertised_entries():
    """The router's candidate grid and the pool's insert grid agree, so
    an advertised entry hash is discoverable from the raw prompt."""
    toks = list(range(100))
    pc = PrefixCache(4, 64, min_tokens=16)
    pc.insert(toks)  # entry at length 64
    assert pc.hashes() == [candidate_hashes(toks, 16)[0]]
    assert prefix_hash(toks[:64]) == pc.hashes()[0]


# ------------------------------------------- suffix-prefill equivalence


def test_suffix_prefill_matches_full_prefill():
    """Greedy tokens are identical whether a prompt is prefilled whole
    or spliced (prefix from cache) + suffix-prefilled: the mask over the
    spliced region is exact."""
    import jax.numpy as jnp

    from ray_tpu.models import llama_decode as ld

    cfg, params = _tiny()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, 24).astype(np.int32)
    full = ld.init_cache(cfg, 1, 64)
    logits_full, full = ld.prefill(params, jnp.asarray(prompt[None]),
                                   full, cfg)
    p = 16
    spliced = ld.init_cache(cfg, 1, 64)
    _, spliced = ld.prefill(params, jnp.asarray(prompt[None, :p]),
                            spliced, cfg)
    suffix = np.zeros((1, 16), np.int32)
    suffix[0, :len(prompt) - p] = prompt[p:]
    logits_suf, spliced = ld.prefill_suffix(
        params, jnp.asarray(suffix), spliced, cfg,
        jnp.array([p], np.int32), jnp.array([len(prompt)], np.int32))
    np.testing.assert_allclose(np.asarray(logits_suf),
                               np.asarray(logits_full),
                               rtol=2e-2, atol=2e-2)
    # Greedy continuation is token-for-token identical.
    ta = jnp.argmax(logits_full, -1).astype(jnp.int32)
    tb = jnp.argmax(logits_suf, -1).astype(jnp.int32)
    for _ in range(6):
        assert int(ta[0]) == int(tb[0])
        la, full = ld.decode_step(params, full, ta, cfg)
        lb, spliced = ld.decode_step(params, spliced, tb, cfg)
        ta = jnp.argmax(la, -1).astype(jnp.int32)
        tb = jnp.argmax(lb, -1).astype(jnp.int32)


def test_engine_prefix_hits_bit_exact():
    """Continuous batching with the prefix cache ON produces exactly the
    solo-generate stream for every request, across cold insert, full-hit
    and partial-hit admissions."""
    from ray_tpu.models import llama_decode
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    rng = np.random.default_rng(1)
    shared = rng.integers(0, cfg.vocab_size, 20).tolist()
    prompts = [shared + rng.integers(0, cfg.vocab_size, 6).tolist()
               for _ in range(3)]
    # Partial hit: diverges inside the cached entry.
    prompts.append(shared[:9] + rng.integers(0, cfg.vocab_size,
                                             8).tolist())
    eng = DecodeEngine(params, cfg, slots=2, capacity=64,
                       prefix_pool_entries=4, prefix_match_min_tokens=4)
    hits = 0
    for p in prompts:
        req = eng.submit(p, max_new_tokens=5)
        for _ in range(40):
            if req.done.is_set():
                break
            eng.step()
        solo = np.asarray(llama_decode.generate(
            params, np.array([p], np.int32), cfg, max_new_tokens=5))[0]
        assert req.output == list(solo), (req.output, list(solo))
        hits += req.prefix_len > 0
    assert hits == 3  # all but the cold first admission
    stats = eng.prefix.stats()
    assert stats["hits"] == 3 and stats["prefill_tokens_saved"] > 0
    # Partial-hit request matched at the divergence point, not beyond.
    assert prompts[-1][:9] == shared[:9]
    eng.shutdown()


def test_engine_prefix_batched_hit_wave():
    """A whole admission wave of prefix hits (batched suffix prefill,
    padded to a power of two) stays bit-exact."""
    from ray_tpu.models import llama_decode
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    rng = np.random.default_rng(2)
    shared = rng.integers(0, cfg.vocab_size, 16).tolist()
    eng = DecodeEngine(params, cfg, slots=4, capacity=64,
                       prefix_pool_entries=4, prefix_match_min_tokens=4)
    warm = eng.submit(shared + [7, 7], max_new_tokens=1)
    while not warm.done.is_set():
        eng.step()
    prompts = [shared + rng.integers(0, cfg.vocab_size, 5).tolist()
               for _ in range(3)]  # wave of 3 -> padded to n=4
    reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    for _ in range(40):
        if all(r.done.is_set() for r in reqs):
            break
        eng.step()
    assert all(r.prefix_len == 16 for r in reqs)
    for req, p in zip(reqs, prompts):
        solo = np.asarray(llama_decode.generate(
            params, np.array([p], np.int32), cfg, max_new_tokens=4))[0]
        assert req.output == list(solo), (req.output, list(solo))
    eng.shutdown()


def test_engine_disabled_pool_allocates_nothing():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=64,
                       prefix_pool_entries=0)
    assert eng.prefix is None and eng._pool is None
    req = eng.submit([1, 2, 3], max_new_tokens=3)
    for _ in range(10):
        if req.done.is_set():
            break
        eng.step()
    assert len(req.output) == 3
    assert "prefix" not in eng.stats()
    eng.shutdown()


def test_engine_load_counts_backlog():
    """Replica load = occupied slots + pending queue depth: a saturated
    engine with a deep queue must not look idle to the autoscaler."""
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=64,
                       prefix_pool_entries=0)
    reqs = [eng.submit([i + 1, 2], max_new_tokens=8) for i in range(5)]
    eng.step()  # admit 2, leave 3 queued
    s = eng.stats()
    assert s["active"] == 2 and s["queued"] == 3 and s["load"] == 5
    assert s["slots"] == 2
    for _ in range(60):
        if all(r.done.is_set() for r in reqs):
            break
        eng.step()
    assert eng.stats()["load"] == 0
    eng.shutdown()


# ------------------------------------------------------ affinity routing


def test_router_prefers_prefix_resident_replica():
    from ray_tpu.serve.deployment import _Router

    toks = list(range(64))
    h = prefix_hash(np.asarray(toks[:64], np.int32))
    router = object.__new__(_Router)
    router._lock = threading.Lock()
    router._max_ongoing = 8
    router._inflight = {}
    router._replicas = [
        {"id": "cold", "models": set(), "prefixes": set()},
        {"id": "warm", "models": set(), "prefixes": {h}},
    ]
    hashes = candidate_hashes(toks, 16)
    assert hashes[0] == h
    for _ in range(4):
        assert router._pick("", hashes)["id"] == "warm"
    # Saturated warm replica: affinity yields to least-loaded.
    router._inflight["warm"] = 8
    assert router._pick("", hashes)["id"] == "cold"


def test_affinity_hashes_extraction():
    from ray_tpu.core.config import config as rt_config
    from ray_tpu.serve.deployment import _affinity_hashes

    toks = list(range(40))
    hashes = _affinity_hashes(({"tokens": toks},))
    assert hashes == candidate_hashes(toks,
                                      rt_config.prefix_match_min_tokens)
    assert _affinity_hashes(()) is None
    assert _affinity_hashes(("not-a-dict",)) is None
    assert _affinity_hashes(({"no_tokens": 1},)) is None
    old = rt_config.prefix_affinity_enabled
    try:
        rt_config.prefix_affinity_enabled = False
        assert _affinity_hashes(({"tokens": toks},)) is None
    finally:
        rt_config.prefix_affinity_enabled = old
