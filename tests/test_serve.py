"""Serve tests (model: reference ``serve/tests/test_serve.py`` family)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield
    serve.shutdown()


def test_basic_deployment(serve_cluster):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    handle = serve.run(Doubler.bind())
    assert handle.remote(21).result(timeout=60) == 42


def test_deployment_with_init_args_and_methods(serve_cluster):
    @serve.deployment
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting

        def __call__(self, name):
            return f"{self.greeting}, {name}!"

        def reverse(self, name):
            return name[::-1]

    handle = serve.run(Greeter.bind("Hello"))
    assert handle.remote("tpu").result(timeout=60) == "Hello, tpu!"
    assert handle.reverse.remote("abc").result(timeout=60) == "cba"


def test_multiple_replicas_all_serve(serve_cluster):
    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(WhoAmI.bind())
    pids = {handle.remote(None).result(timeout=60) for _ in range(20)}
    assert len(pids) >= 2, f"expected multiple replicas used, got {pids}"


def test_http_proxy(serve_cluster):
    @serve.deployment
    class Adder:
        def __call__(self, body):
            return {"sum": body["a"] + body["b"]}

    serve.run(Adder.bind(), name="Adder")
    host, port = serve.start_http()
    req = urllib.request.Request(
        f"http://{host}:{port}/Adder",
        data=json.dumps({"a": 2, "b": 40}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert json.loads(resp.read()) == {"sum": 42}


def test_batching(serve_cluster):
    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def __call__(self, items):
            # items is a list; return list of (value, batch_size)
            return [(x * 10, len(items)) for x in items]

    handle = serve.run(Batched.bind())
    futures = [handle.remote(i) for i in range(8)]
    results = [f.result(timeout=60) for f in futures]
    values = sorted(r[0] for r in results)
    assert values == [i * 10 for i in range(8)]
    assert max(r[1] for r in results) > 1, "no batching happened"


def test_autoscaling_up(serve_cluster):
    @serve.deployment(autoscaling_config=serve.AutoscalingConfig(
        min_replicas=1, max_replicas=3, target_ongoing_requests=1,
        upscale_delay_s=0.1))
    class Slow:
        def __call__(self, _):
            time.sleep(0.8)
            return "done"

    handle = serve.run(Slow.bind(), name="Slow")
    futures = [handle.remote(None) for _ in range(12)]
    deadline = time.monotonic() + 20
    scaled = False
    while time.monotonic() < deadline:
        if serve.status()["Slow"]["replicas"] >= 2:
            scaled = True
            break
        time.sleep(0.2)
    for f in futures:
        f.result(timeout=120)
    assert scaled, f"never scaled up: {serve.status()}"


def test_redeploy_replaces(serve_cluster):
    @serve.deployment
    class V:
        def __init__(self, version):
            self.v = version

        def __call__(self, _):
            return self.v

    handle = serve.run(V.bind(1), name="V")
    assert handle.remote(None).result(timeout=60) == 1
    handle2 = serve.run(V.bind(2), name="V")
    assert handle2.remote(None).result(timeout=60) == 2


def test_controller_survives_deployer_exit(serve_cluster):
    # Deploy from a WORKER process (which exits after the task): the
    # control plane lives in the ServeController actor, so a fresh handle
    # in this process keeps serving (reference: controller-as-actor,
    # serve/_private/controller.py:86).
    @ray_tpu.remote
    def deployer():
        from ray_tpu import serve as s

        @s.deployment
        class Echo:
            def __call__(self, x):
                return {"echo": x}

        s.run(Echo.bind(), name="survivor")
        return "deployed"

    assert ray_tpu.get(deployer.remote(), timeout=120) == "deployed"
    handle = serve.get_deployment_handle("survivor")
    assert handle.remote("hi").result(timeout=60) == {"echo": "hi"}
    assert serve.status()["survivor"]["replicas"] >= 1


def test_replica_death_heals(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Pid:
        def __call__(self, _):
            import os

            return os.getpid()

    handle = serve.run(Pid.bind(), name="heal")
    pids = {handle.remote(None).result(timeout=60) for _ in range(8)}
    assert pids
    # Kill one replica's process; the controller's reconcile loop must
    # replace it and requests keep succeeding.
    import signal

    os_pid = next(iter(pids))
    import os as _os

    _os.kill(os_pid, signal.SIGKILL)
    deadline = time.time() + 60
    while True:
        try:
            result = handle.remote(None).result(timeout=30)
            if result != os_pid:
                break
        except Exception:
            pass
        assert time.time() < deadline, "requests never recovered"
        time.sleep(0.5)
    deadline = time.time() + 60
    while serve.status()["heal"]["replicas"] < 2:
        assert time.time() < deadline, "dead replica never replaced"
        time.sleep(0.5)


def test_multiplexed_model_routing(serve_cluster):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            self.loads.append(model_id)
            return f"model:{model_id}"

        def __call__(self, x):
            import os

            model = self.get_model()
            return {"model": model, "pid": os.getpid(),
                    "mid": serve.get_multiplexed_model_id()}

    handle = serve.run(MultiModel.bind(), name="mux")
    r1 = handle.options(multiplexed_model_id="a").remote(1).result(timeout=60)
    assert r1["model"] == "model:a" and r1["mid"] == "a"
    # Give the controller a reconcile tick to learn residency, then check
    # affinity: repeated "a" requests stay on the warm replica.
    time.sleep(1.0)
    pids = {handle.options(multiplexed_model_id="a").remote(i).result(
        timeout=60)["pid"] for i in range(6)}
    assert len(pids) == 1, f"model-a requests scattered: {pids}"


def test_scale_up_propagates_to_handles(serve_cluster):
    @serve.deployment(num_replicas=1)
    class Sleepy:
        def __call__(self, x):
            import os

            return os.getpid()

    handle = serve.run(Sleepy.bind(), name="scaler")
    assert len({handle.remote(0).result(timeout=60)
                for _ in range(4)}) == 1
    # Redeploy with 3 replicas: the pubsub snapshot must reach this
    # process's router without re-running serve.run here.
    serve.run(Sleepy.options(num_replicas=3).bind(), name="scaler")
    deadline = time.time() + 60
    while True:
        pids = {handle.remote(0).result(timeout=60) for _ in range(12)}
        if len(pids) >= 2:
            break
        assert time.time() < deadline, "scale-up never reached the router"
        time.sleep(0.5)


def test_unknown_deployment_fails_fast(serve_cluster):
    @serve.deployment
    class Real:
        def __call__(self, x):
            return x

    serve.run(Real.bind(), name="real")
    t0 = time.time()
    with pytest.raises(KeyError):
        serve.get_deployment_handle("nope").remote(1).result(timeout=30)
    assert time.time() - t0 < 10, "unknown deployment stalled"


def test_deployment_composition(serve_cluster):
    # Model composition: a deployment holding handles to other deployments
    # (reference: deployment graphs / DeploymentHandle passing).
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Ensemble:
        def __init__(self, pre, model):
            self.pre = pre
            self.model = model

        def __call__(self, x):
            staged = self.pre.remote(x).result(timeout=30)
            return self.model.remote(staged).result(timeout=30)

    pre = serve.run(Preprocess.bind(), name="pre")
    model = serve.run(Model.bind(), name="model")
    app = serve.run(Ensemble.bind(pre, model), name="ensemble")
    assert app.remote(5).result(timeout=60) == 11


@pytest.mark.timeout_s(360)
def test_jitted_llama_replica_with_bucketed_batching(serve_cluster):
    """A replica hosting a jitted debug-Llama forward behind bucketed
    dynamic batching (VERDICT round-1 #8: the TPU-serving shape — static
    bucket sizes so XLA compiles a handful of programs, not one per batch
    size)."""

    @serve.deployment(max_ongoing_requests=16)
    class LlamaServer:
        def __init__(self):
            import jax

            from ray_tpu.models import llama

            self.cfg = llama.PRESETS["debug"]
            self.params = llama.init_params(self.cfg, jax.random.key(0))
            self.fwd = jax.jit(
                lambda p, t: llama.forward(p, t, self.cfg))
            self.shapes_seen = set()

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05,
                     pad_to_buckets=[2, 4, 8])
        def predict(self, token_lists):
            # token_lists arrives PADDED to a bucket size; the batched fn
            # runs the jitted model on the full bucket and returns one
            # response per padded row (the queue slices off the padding).
            import numpy as np

            toks = np.asarray(token_lists, dtype=np.int32)
            self.shapes_seen.add(toks.shape[0])
            logits = self.fwd(self.params, toks)
            return [float(np.asarray(row).sum())
                    for row in np.asarray(logits)]

        def __call__(self, token_list):
            return self.predict(token_list)

        def buckets(self, _):
            return sorted(self.shapes_seen)

    handle = serve.run(LlamaServer.bind(), name="llama_srv")
    seq = [1, 2, 3, 4] * 8  # 32 tokens
    futs = [handle.remote(seq) for _ in range(12)]
    outs = [f.result(timeout=240) for f in futs]
    assert all(isinstance(o, float) for o in outs)
    # All requests for the same input agree (batched through one jit).
    assert max(outs) - min(outs) < 1e-3
    buckets = handle.options(method_name="buckets").remote(None).result(
        timeout=60)
    assert set(buckets) <= {2, 4, 8}, buckets  # only bucket shapes compiled


def test_deploy_from_config_file(ray_start_regular, tmp_path):
    """Declarative deployment from a YAML config (reference: serve deploy
    config.yaml / ServeDeploySchema)."""
    import sys
    import textwrap

    from ray_tpu.serve.build import deploy_config

    mod = tmp_path / "my_app_mod.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment
        class Greeter:
            def __init__(self, greeting="hi"):
                self.greeting = greeting

            def __call__(self, name):
                return f"{self.greeting}, {name}!"

        app = Greeter
    """))
    cfg = tmp_path / "serve_config.yaml"
    cfg.write_text(textwrap.dedent("""
        applications:
          - name: greeter
            import_path: my_app_mod:app
            num_replicas: 2
            init_kwargs:
              greeting: hello
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        handles = deploy_config(str(cfg))
        assert len(handles) == 1
        assert handles[0].remote("tpu").result(timeout=60) == "hello, tpu!"
        from ray_tpu import serve

        st = serve.status()
        assert "greeter" in st
        serve.shutdown()
    finally:
        sys.path.remove(str(tmp_path))


def test_http_route_prefix(ray_start_regular):
    """Custom route_prefix routes through the HTTP proxy (longest-prefix
    match against the controller's route table)."""
    import json
    import urllib.request

    from ray_tpu import serve

    @serve.deployment
    class Sum:
        def __call__(self, xs):
            return {"total": sum(xs)}

    serve.run(Sum.bind(), name="summer", route_prefix="/api/v1/sum")
    host, port = serve.start_http()
    req = urllib.request.Request(
        f"http://{host}:{port}/api/v1/sum",
        data=json.dumps([1, 2, 3]).encode(),
        headers={"Content-Type": "application/json"})
    out = json.load(urllib.request.urlopen(req, timeout=30))
    assert out == {"total": 6}
    # Default route (/<name>) still works too.
    req = urllib.request.Request(
        f"http://{host}:{port}/summer",
        data=json.dumps([4, 5]).encode(),
        headers={"Content-Type": "application/json"})
    assert json.load(urllib.request.urlopen(req, timeout=30)) == {"total": 9}
    serve.shutdown()


def test_route_prefix_redeploy_converges(ray_start_regular):
    """Re-deploying with a new route_prefix retires the old route (the
    declarative config workflow must converge)."""
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    @serve.deployment
    class V:
        def __call__(self, x):
            return x

    serve.run(V.bind(), name="v", route_prefix="/v1")
    controller = serve_api.get_or_create_controller()
    import ray_tpu as rt

    routes = rt.get(controller.get_routes.remote(), timeout=30)
    assert routes == {"/v1": "v"}

    serve.run(V.bind(), name="v", route_prefix="v2")  # slash-less input
    routes = rt.get(controller.get_routes.remote(), timeout=30)
    assert routes == {"/v2": "v"}  # normalized AND old route retired
    from ray_tpu.serve.proxy import _RouteTable

    table = _RouteTable()
    assert table.resolve("/v2/anything") == "v"
    assert table.resolve("/v1") is None
    serve.shutdown()


# ------------------------------------------------- streaming + draining
# (VERDICT r2 Missing #9; reference: serve/_private/proxy.py streaming
# responses + proxy draining)


def test_handle_streaming_generator(serve_cluster):
    @serve.deployment
    class TokenStream:
        def __call__(self, n):
            for i in range(n):
                yield {"token": i}

    serve.run(TokenStream.bind(), name="tok")
    handle = serve.get_deployment_handle("tok")
    items = list(handle.stream(7))
    assert items == [{"token": i} for i in range(7)]
    # Early exit cancels the stream and frees the replica slot.
    it = handle.stream(1000)
    assert next(it) == {"token": 0}
    it.close()
    deadline = time.monotonic() + 30
    while True:
        ongoing = sum(d["ongoing"] for d in serve.status().values())
        if ongoing == 0:
            break
        assert time.monotonic() < deadline, serve.status()
        time.sleep(0.5)


def test_http_streaming_chunked(serve_cluster):
    import urllib.request

    @serve.deployment
    class Counter:
        def __call__(self, n):
            for i in range(n):
                yield i * 10

    serve.run(Counter.bind(), name="count")
    host, port = serve.start_http()
    req = urllib.request.Request(
        f"http://{host}:{port}/count", data=json.dumps(5).encode(),
        headers={"X-Serve-Stream": "1"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers.get("Content-Type") == "application/jsonlines"
        lines = [json.loads(ln) for ln in resp.read().splitlines() if ln]
    assert lines == [0, 10, 20, 30, 40]


def test_http_shutdown_drains_in_flight(serve_cluster):
    import threading
    import urllib.request

    @serve.deployment
    class Slow:
        def __call__(self, x):
            time.sleep(2.0)
            return x + 1

    serve.run(Slow.bind(), name="slow")
    host, port = serve.start_http()
    results = {}

    def call():
        req = urllib.request.Request(
            f"http://{host}:{port}/slow", data=json.dumps(41).encode())
        with urllib.request.urlopen(req, timeout=30) as resp:
            results["value"] = json.loads(resp.read())

    t = threading.Thread(target=call)
    t.start()
    time.sleep(0.5)  # request in flight
    serve.shutdown(drain_timeout_s=15.0)  # must NOT cut the request off
    t.join(timeout=30)
    assert results.get("value") == 42


# --------------------------------------------- per-node proxy data plane
# (VERDICT r3 Missing #1; reference: serve/_private/proxy.py:131,
# proxy_state.py — managed ProxyActor per node, supervised by the serve
# controller)


def test_proxy_per_node(ray_start_cluster):
    """Ingress runs as one ProxyActor per alive node — every proxy serves
    every route (any node can be the ingress point)."""
    cluster = ray_start_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(30)
    ray_tpu.init(address=cluster.address)

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"echo": x}

    serve.run(Echo.bind(), name="echo")
    serve.start_http()
    addrs = serve.http_addresses()
    assert len(addrs) == 3, addrs  # one proxy per node
    for node_hex, (host, port) in addrs.items():
        req = urllib.request.Request(
            f"http://{host}:{port}/echo", data=json.dumps(node_hex).encode())
        out = json.load(urllib.request.urlopen(req, timeout=30))
        assert out == {"echo": node_hex}
    # Proxies are visible in the status surface the CLI prints.
    pstat = serve.proxy_status()
    assert set(pstat) == set(addrs)
    serve.shutdown()


def test_ingress_survives_driver_exit(ray_start_cluster):
    """The data plane lives in proxy ACTORS, not the deploying driver: a
    subprocess driver deploys + enables HTTP and exits; the app stays
    servable over the same proxy address."""
    import subprocess
    import sys

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes(30)

    script = """
import json, sys
import ray_tpu
from ray_tpu import serve
ray_tpu.init(address=%r)

@serve.deployment
class Echo:
    def __call__(self, x):
        return {"from_actor": x}

serve.run(Echo.bind(), name="survivor")
host, port = serve.start_http()
print(json.dumps([host, port]))
sys.stdout.flush()
""" % (cluster.address,)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    host, port = json.loads(proc.stdout.strip().splitlines()[-1])

    # Driver is gone; ingress + replicas keep serving.
    req = urllib.request.Request(
        f"http://{host}:{port}/survivor", data=json.dumps("hi").encode())
    out = json.load(urllib.request.urlopen(req, timeout=60))
    assert out == {"from_actor": "hi"}
    ray_tpu.init(address=cluster.address)
    serve.shutdown()


def test_proxy_healed_after_kill(serve_cluster):
    """The serve controller health-checks proxies and replaces dead ones
    (reference: proxy_state.py recovery)."""

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    serve.run(Echo.bind(), name="ping")
    serve.start_http()
    addrs = serve.http_addresses()
    assert len(addrs) == 1
    (node_hex, old_addr), = addrs.items()

    # Kill the proxy actor out from under the controller.
    from ray_tpu.serve.controller import get_or_create_controller
    controller = get_or_create_controller()
    pstat = ray_tpu.get(controller.proxy_status.remote(), timeout=30)
    assert node_hex in pstat
    # Find and kill the proxy actor via the cluster actor table.
    from ray_tpu.core.runtime import get_core_worker
    actors = get_core_worker().controller.call("list_actors")
    victims = [a for a in actors
               if a["info"].get("class_name") == "ProxyActor"
               and a["state"] == "ALIVE"]
    assert victims, actors
    import ray_tpu as rt
    from ray_tpu.core.actor import ActorHandle
    from ray_tpu.core.ids import ActorID
    rt.kill(ActorHandle(ActorID(victims[0]["actor_id"])))

    # Controller notices and brings up a replacement on the same node.
    deadline = time.monotonic() + 60
    while True:
        new_addrs = serve.proxy_status()
        live = {n: v for n, v in new_addrs.items() if v["addr"]}
        if node_hex in live and tuple(live[node_hex]["addr"]) != tuple(old_addr):
            break
        assert time.monotonic() < deadline, new_addrs
        time.sleep(0.5)
    host, port = live[node_hex]["addr"]
    req = urllib.request.Request(
        f"http://{host}:{port}/ping", data=json.dumps(7).encode())
    assert json.load(urllib.request.urlopen(req, timeout=30)) == 7
