"""Serve tests (model: reference ``serve/tests/test_serve.py`` family)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield
    serve.shutdown()


def test_basic_deployment(serve_cluster):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    handle = serve.run(Doubler.bind())
    assert handle.remote(21).result(timeout=60) == 42


def test_deployment_with_init_args_and_methods(serve_cluster):
    @serve.deployment
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting

        def __call__(self, name):
            return f"{self.greeting}, {name}!"

        def reverse(self, name):
            return name[::-1]

    handle = serve.run(Greeter.bind("Hello"))
    assert handle.remote("tpu").result(timeout=60) == "Hello, tpu!"
    assert handle.reverse.remote("abc").result(timeout=60) == "cba"


def test_multiple_replicas_all_serve(serve_cluster):
    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(WhoAmI.bind())
    pids = {handle.remote(None).result(timeout=60) for _ in range(20)}
    assert len(pids) >= 2, f"expected multiple replicas used, got {pids}"


def test_http_proxy(serve_cluster):
    @serve.deployment
    class Adder:
        def __call__(self, body):
            return {"sum": body["a"] + body["b"]}

    serve.run(Adder.bind(), name="Adder")
    host, port = serve.start_http()
    req = urllib.request.Request(
        f"http://{host}:{port}/Adder",
        data=json.dumps({"a": 2, "b": 40}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert json.loads(resp.read()) == {"sum": 42}


def test_batching(serve_cluster):
    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def __call__(self, items):
            # items is a list; return list of (value, batch_size)
            return [(x * 10, len(items)) for x in items]

    handle = serve.run(Batched.bind())
    futures = [handle.remote(i) for i in range(8)]
    results = [f.result(timeout=60) for f in futures]
    values = sorted(r[0] for r in results)
    assert values == [i * 10 for i in range(8)]
    assert max(r[1] for r in results) > 1, "no batching happened"


def test_autoscaling_up(serve_cluster):
    @serve.deployment(autoscaling_config=serve.AutoscalingConfig(
        min_replicas=1, max_replicas=3, target_ongoing_requests=1,
        upscale_delay_s=0.1))
    class Slow:
        def __call__(self, _):
            time.sleep(0.8)
            return "done"

    handle = serve.run(Slow.bind(), name="Slow")
    futures = [handle.remote(None) for _ in range(12)]
    deadline = time.monotonic() + 20
    scaled = False
    while time.monotonic() < deadline:
        if serve.status()["Slow"]["replicas"] >= 2:
            scaled = True
            break
        time.sleep(0.2)
    for f in futures:
        f.result(timeout=120)
    assert scaled, f"never scaled up: {serve.status()}"


def test_redeploy_replaces(serve_cluster):
    @serve.deployment
    class V:
        def __init__(self, version):
            self.v = version

        def __call__(self, _):
            return self.v

    handle = serve.run(V.bind(1), name="V")
    assert handle.remote(None).result(timeout=60) == 1
    handle2 = serve.run(V.bind(2), name="V")
    assert handle2.remote(None).result(timeout=60) == 2
