"""Core task/object API tests (model: reference ``python/ray/tests/test_basic.py``)."""

import numpy as np
import pytest

import ray_tpu


@ray_tpu.remote
def add(a, b):
    return a + b


@ray_tpu.remote
def echo(x):
    return x


@ray_tpu.remote
def fail():
    raise ValueError("boom")


@ray_tpu.remote(num_returns=2)
def two_returns():
    return 1, 2


@ray_tpu.remote
def nested(x):
    ref = echo.remote(x + 1)
    return ray_tpu.get(ref)


def test_put_get(ray_start_regular):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_numpy_zero_copy(ray_start_regular):
    arr = np.arange(1024, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(ray_start_regular):
    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(ray_start_regular):
    a = ray_tpu.put(10)
    b = add.remote(a, 5)
    c = add.remote(b, a)
    assert ray_tpu.get(c) == 25


def test_many_tasks(ray_start_regular):
    refs = [add.remote(i, i) for i in range(50)]
    assert ray_tpu.get(refs) == [2 * i for i in range(50)]


def test_task_error_propagates(ray_start_regular):
    with pytest.raises(ray_tpu.TaskError) as exc_info:
        ray_tpu.get(fail.remote())
    assert "boom" in str(exc_info.value)


def test_error_through_dependency(ray_start_regular):
    bad = fail.remote()
    downstream = add.remote(bad, 1)
    with pytest.raises(ray_tpu.TaskError):
        ray_tpu.get(downstream)


def test_multiple_returns(ray_start_regular):
    r1, r2 = two_returns.remote()
    assert ray_tpu.get(r1) == 1
    assert ray_tpu.get(r2) == 2


def test_nested_tasks(ray_start_regular):
    assert ray_tpu.get(nested.remote(1)) == 2


def test_wait(ray_start_regular):
    import time

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    fast_ref = echo.remote("fast")
    slow_ref = slow.remote()
    ready, pending = ray_tpu.wait([fast_ref, slow_ref], num_returns=1,
                                  timeout=10)
    assert ready == [fast_ref]
    assert pending == [slow_ref]


def test_get_timeout(ray_start_regular):
    import time

    @ray_tpu.remote
    def slow():
        time.sleep(30)

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.5)


def test_options_num_returns(ray_start_regular):
    @ray_tpu.remote
    def three():
        return 1, 2, 3

    refs = three.options(num_returns=3).remote()
    assert ray_tpu.get(refs) == [1, 2, 3]


def test_cluster_resources(ray_start_regular):
    assert ray_tpu.cluster_resources().get("CPU") == 4.0


def test_large_object_roundtrip(ray_start_regular):
    arr = np.random.rand(1 << 20)  # 8 MB
    out = ray_tpu.get(echo.remote(arr))
    np.testing.assert_array_equal(arr, out)


def test_util_state_api(ray_start_regular):
    """Python state surface (reference: ray.util.state api.py)."""
    from ray_tpu.util import state

    @ray_tpu.remote
    def touch():
        return 1

    assert ray_tpu.get(touch.remote()) == 1
    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    objs = state.list_objects()
    assert objs and objs[0]["store_capacity_bytes"] > 0
    assert state.cluster_resources()["CPU"] == 4.0
    assert state.available_resources()["CPU"] <= 4.0
    # Task events land asynchronously; summarize sees them eventually.
    import time as _t

    deadline = _t.monotonic() + 30
    total = 0
    while total == 0 and _t.monotonic() < deadline:
        total = state.summarize_tasks()["total"]
        _t.sleep(0.2)
    assert total >= 1


def test_lease_pipelined_batches_isolate_errors(ray_start_regular):
    """Same-shape ready tasks ride the lease-pipelined batch path (round
    5: push_task_batch); a failing task inside a batch must fail ONLY
    itself, and results keep their identities."""
    @ray_tpu.remote
    def maybe_fail(i):
        if i % 50 == 7:
            raise ValueError(f"boom{i}")
        return i * 3

    refs = [maybe_fail.remote(i) for i in range(200)]
    for i, r in enumerate(refs):
        if i % 50 == 7:
            with pytest.raises(Exception, match=f"boom{i}"):
                ray_tpu.get(r, timeout=60)
        else:
            assert ray_tpu.get(r, timeout=60) == i * 3


def test_non_retriable_tasks_bypass_pipeline(ray_start_regular):
    """max_retries=0 tasks take the solo lease path (a reused dead worker
    would otherwise turn a never-executed push into a terminal crash) —
    and still execute correctly."""
    @ray_tpu.remote(max_retries=0)
    def once(i):
        return i + 100

    assert ray_tpu.get([once.remote(i) for i in range(20)],
                       timeout=60) == list(range(100, 120))
