"""Off-policy RL tests: replay buffers, DQN (run-to-reward), offline BC.

Reference model: rllib per-algorithm test dirs + replay-buffer unit tests
+ offline BC from logged data.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import BCConfig, DQNConfig, ReplayBuffer, SumTree
from ray_tpu.rl.dqn import rollout_to_transitions


def test_sum_tree_proportional():
    tree = SumTree(8)
    tree.set([0, 1, 2], [1.0, 3.0, 6.0])
    assert tree.total == pytest.approx(10.0)
    rng = np.random.default_rng(0)
    counts = np.zeros(8)
    draws = 4000
    idx = np.concatenate([tree.sample(8, rng) for _ in range(draws // 8)])
    for i in idx:
        counts[i] += 1
    freq = counts / draws
    assert freq[2] > freq[1] > freq[0] > 0
    assert freq[2] == pytest.approx(0.6, abs=0.05)
    assert counts[3:].sum() == 0  # zero-priority slots never sampled


def test_replay_buffer_wraparound_and_sampling():
    buf = ReplayBuffer(capacity=10)
    for start in range(0, 25, 5):
        buf.add({"x": np.arange(start, start + 5, dtype=np.int64)})
    assert len(buf) == 10
    batch, idx, w = buf.sample(32)
    # Only the newest 10 values survive the ring.
    assert batch["x"].min() >= 15
    assert np.all(w == 1.0)


def test_prioritized_replay_prefers_high_td():
    buf = ReplayBuffer(capacity=16, prioritized=True, seed=1)
    buf.add({"x": np.arange(16, dtype=np.int64)})
    # Slot 5 gets a huge TD error, everything else tiny.
    buf.update_priorities(np.arange(16), np.full(16, 1e-3))
    buf.update_priorities(np.array([5]), np.array([10.0]))
    batch, idx, w = buf.sample(256)
    frac5 = np.mean(batch["x"] == 5)
    assert frac5 > 0.5  # dominates sampling
    assert w.min() > 0 and w.max() == pytest.approx(1.0)
    # IS weight of the over-sampled slot is the smallest.
    assert w[batch["x"] == 5].mean() < w[batch["x"] != 5].mean()


def test_rollout_to_transitions_drops_synthetic_rows():
    T, N = 4, 1
    obs = np.arange(T * N).reshape(T, N).astype(np.float32)[..., None]
    ro = {
        "obs": obs,
        "actions": np.zeros((T, N), np.int64),
        "rewards": np.ones((T, N), np.float32),
        "dones": np.array([[0], [1], [0], [0]], np.float32),
        "valids": np.array([[1], [1], [0], [1]], np.float32),
    }
    out = rollout_to_transitions(ro)
    # Row 2 is the autoreset step -> dropped; row 3 has no successor.
    assert len(out["rewards"]) == 2
    np.testing.assert_allclose(out["dones"], [0, 1])
    np.testing.assert_allclose(out["next_obs"][:, 0], [1, 2])


def test_dqn_single_iteration(ray_start_regular):
    algo = DQNConfig().environment("CartPole-v1").env_runners(
        2, num_envs_per_runner=2).training(
        rollout_length=16, learning_starts=32, batch_size=32,
        train_batches_per_iter=4).build()
    try:
        m1 = algo.train()
        assert m1["env_steps_this_iter"] > 0
        assert m1["buffer_size"] > 0
        for _ in range(3):
            m = algo.train()
        assert m["learner_steps"] > 0 and "loss" in m
        assert m["epsilon"] < algo.config.epsilon_initial
    finally:
        algo.stop()


@pytest.mark.slow  # 38 s: DQN replay-buffer convergence soak
@pytest.mark.timeout_s(420)
def test_dqn_learns_cartpole(ray_start_regular):
    """Run-to-reward, UN-SKIPPED in PR 10: the PR 3 triage was right
    that the 2-runner plateau (best=52 over 80 iterations) was not a
    budget problem — it was replay-stream correlation. On the Podracer
    substrate (4 RolloutActors x 4 envs feeding prioritized replay
    through the object plane, one pjit learner, pubsub weight fan-out)
    the SAME hyperparameters and seed clear the bar: probed best=151
    at iteration 33, ~19 s wall on the 1-core CI box.

    This is also the off-policy half of the ISSUE 10 acceptance e2e:
    >= 4 RolloutActors + pjit learner to the reward bar, with the
    object-plane descriptor contract, per-actor version monotonicity,
    and leak-free shutdown asserted on the REAL learning run."""
    from ray_tpu.rl.distributed import DESCRIPTOR_BYTE_BUDGET

    algo = DQNConfig().environment("CartPole-v1").distributed_rollouts(
        4, num_envs_per_actor=4).training(
        rollout_length=64, lr=1e-3, batch_size=128,
        learning_starts=500, train_batches_per_iter=48,
        target_update_interval=100, epsilon_decay_steps=6000,
        prioritized_replay=True, seed=2).build()
    try:
        best, first = 0.0, None
        metrics = {}
        for _ in range(60):
            metrics = algo.train()
            ret = metrics.get("episode_return_mean")
            if ret is not None:
                if first is None:
                    first = ret
                best = max(best, ret)
            if best >= 120.0:
                break
        assert first is not None
        assert best >= 100.0, f"DQN failed to learn: first={first}, best={best}"
        # Acceptance contracts, asserted on the learning run itself:
        assert algo.plane.monotonic_violations == 0
        # Fan-out version clock: initial publish + one per iteration.
        assert metrics["weights_version"] == \
            metrics["training_iteration"] + 1
        rl = metrics["rl"]
        assert rl["env_steps"] > 0 and "queue_depth" in rl
        assert rl["shard_desc_bytes"]["p99"] <= DESCRIPTOR_BYTE_BUDGET
        assert rl["shard_desc_bytes"]["count"] >= rl["shards"]
        assert rl["staleness"]["count"] == rl["shards"]
        assert rl["learner_update_s"]["count"] == metrics["learner_steps"]
    finally:
        algo.stop()
    assert algo.last_leak_report["queue_depth"] == 0
    assert algo.last_leak_report["intake_alive"] is False


@pytest.mark.slow  # 7s: offline-clone soak; offpolicy machinery stays
# via the single-iteration + connector tests; PR 18 rebudget
@pytest.mark.timeout_s(420)
def test_bc_clones_policy_offline(ray_start_regular):
    """Offline pipeline: train PPO briefly, record its experience into a
    Dataset, clone with BC, and check the clone acts like the teacher
    (action accuracy high, eval return >= random baseline)."""
    from ray_tpu.rl import PPOConfig, collect_dataset

    teacher = PPOConfig().environment("CartPole-v1").env_runners(
        2, num_envs_per_runner=4).training(
        rollout_length=128, minibatch_size=256, seed=11).build()
    try:
        for _ in range(8):
            teacher.train()
        ds = collect_dataset(teacher, num_rollouts=2)
        assert ds.count() > 500
    finally:
        teacher.stop()

    bc = BCConfig().environment("CartPole-v1").training(
        epochs=6, batch_size=256, seed=11).build(ds)
    metrics = bc.train()
    assert metrics["rows_trained"] > 0
    assert metrics["action_accuracy"] is not None
    # The teacher is stochastic: a deterministic clone's accuracy on
    # SAMPLED teacher actions is capped by teacher entropy — well above
    # chance (0.5) is the meaningful bar.
    assert metrics["action_accuracy"] > 0.55
    ev = bc.evaluate(num_episodes=5)
    assert ev["episode_return_mean"] > 40.0


# ------------------------------------------------------------------- SAC
# (VERDICT r2 #6: an off-policy continuous-control algorithm.
# Reference: rllib/algorithms/sac/sac.py)


def test_sac_single_iteration(ray_start_regular):
    from ray_tpu.rl import SACConfig

    algo = SACConfig(env="Pendulum-v1", seed=3, num_env_runners=1,
                     warmup_steps=64, updates_per_iteration=4).build()
    try:
        m1 = algo.train()
        assert m1["env_steps_this_iter"] > 0
        m2 = algo.train()
        assert m2["env_steps_total"] > m1["env_steps_total"]
        assert "critic_loss" in m2  # learning began after warmup
        # Continuous actions flow end-to-end: buffer holds float actions.
        batch, _, _ = algo.buffer.sample(8)
        assert batch["actions"].dtype == np.float32
        assert batch["actions"].shape[1:] == (1,)
    finally:
        algo.stop()


# Tier-1 budget triage (ISSUE 11): this was the single slowest tier-1
# test at 51.9 s (2026-08-05 profile, suite 801 s vs the 870 s cap) —
# run-to-reward SAC is ~5k jitted updates + env steps on the 1-core
# box, and like CQL above it is update-bound, so parallel rollouts
# can't shrink the wall. Verified passing (best > -600 within budget)
# before slow-marking; it still runs (and passes) outside tier-1, and
# SAC's machinery stays covered in tier-1 by the action-space /
# replay / offline-roundtrip tests in this file.
@pytest.mark.slow
@pytest.mark.timeout_s(400)
def test_sac_learns_pendulum(ray_start_regular):
    """Run-to-reward: SAC pulls Pendulum well above the random baseline
    (~-1220) within a bounded budget. Seeded; the threshold is generous
    because this suite runs on loaded CI boxes."""
    from ray_tpu.rl import SACConfig

    algo = SACConfig(env="Pendulum-v1", seed=1, num_env_runners=2,
                     updates_per_iteration=48, warmup_steps=800).build()
    try:
        best = -float("inf")
        for _ in range(110):
            m = algo.train()
            best = max(best, m.get("episode_return_mean", -float("inf")))
            if best > -600:
                break
        assert best > -600, f"SAC stuck at {best}"
    finally:
        algo.stop()


# ------------------------------------------------------------------- CQL
# (VERDICT r3 #6: offline pipeline + an offline algorithm beyond BC.
# Reference: rllib/algorithms/cql/cql.py + rllib/offline/)


def test_offline_transitions_roundtrip_parquet(ray_start_regular, tmp_path):
    """Transitions Dataset -> parquet -> Dataset -> ReplayBuffer keeps
    every canonical column and row count (reference: offline output
    writers + input readers over ray.data)."""
    from ray_tpu.rl import SACConfig
    from ray_tpu.rl.offline import (TRANSITION_COLUMNS, dataset_to_buffer,
                                    load_transitions, rollouts_to_dataset,
                                    save_transitions)

    algo = SACConfig(env="Pendulum-v1", num_env_runners=1,
                     num_envs_per_runner=2, rollout_length=16,
                     seed=3).build()
    try:
        ds = rollouts_to_dataset(algo, num_rollouts=2)
    finally:
        algo.stop()
    n = ds.count()
    assert n > 30
    save_transitions(ds, str(tmp_path / "logs"))
    back = load_transitions(str(tmp_path / "logs"))
    assert back.count() == n
    buf = dataset_to_buffer(back, seed=0)
    assert len(buf) == n
    batch, _idx, _w = buf.sample(16)
    for col in TRANSITION_COLUMNS:
        assert col in batch and len(batch[col]) == 16
    # Obs keep their feature shape through the tabular round-trip.
    assert batch["obs"].shape[1:] == batch["next_obs"].shape[1:]
    assert batch["obs"].shape[1:] == (3,)


def _scripted_pendulum_dataset(n_episodes: int, noise: float, seed: int):
    """Near-expert behavior data from an energy swing-up + PD-catch
    controller (mean return ~ -135), with Gaussian action noise for state
    coverage. Stored actions use the runner convention ([-1, 1])."""
    import gymnasium as gym

    from ray_tpu import data as rdata

    env = gym.make("Pendulum-v1")
    rng = np.random.default_rng(seed)
    cols = {c: [] for c in ("obs", "actions", "rewards", "next_obs",
                            "terminateds")}
    for ep in range(n_episodes):
        obs, _ = env.reset(seed=seed + ep)
        done = False
        while not done:
            cos_th, sin_th, thdot = obs
            th = np.arctan2(sin_th, cos_th)
            energy = 0.5 * thdot ** 2 + 10.0 * (cos_th - 1.0)
            # SMOOTH blend of PD-catch and energy pumping: a hard switch
            # would make the behavior multi-modal near the switching
            # surface, and no unimodal clone (BC or CQL actor) can fit
            # opposing torques averaged to zero.
            pd = -(10.0 * th + 2.0 * thdot)
            pump = -thdot * energy
            w = (1.0 / (1.0 + np.exp(-10.0 * (cos_th - 0.8)))
                 * 1.0 / (1.0 + np.exp(-4.0 * (4.0 - abs(thdot)))))
            u = w * pd + (1.0 - w) * pump
            u = float(np.clip(u / 2.0 + rng.normal(0.0, noise), -1.0, 1.0))
            nobs, reward, term, trunc, _ = env.step([u * 2.0])
            cols["obs"].append(obs.astype(np.float32))
            cols["actions"].append(np.float32(u))
            cols["rewards"].append(np.float32(reward))
            cols["next_obs"].append(nobs.astype(np.float32))
            cols["terminateds"].append(np.float32(term))
            obs = nobs
            done = term or trunc
    env.close()
    return rdata.from_numpy({
        "obs": np.stack(cols["obs"]),
        "actions": np.asarray(cols["actions"])[:, None],
        "rewards": np.asarray(cols["rewards"]),
        "next_obs": np.stack(cols["next_obs"]),
        "terminateds": np.asarray(cols["terminateds"]),
    })


# Re-probed in PR 10 (the DQN un-skip pass): CQL now PASSES the -900
# bar on this image — first eval -1544, adaptive budget recovers to
# -792 on the 3rd extension — but takes ~144 s wall on the 1-core box,
# which does not fit the tier-1 870 s envelope (suite baseline ~770 s).
# Slow-marked instead of skipped: it runs (and passes) outside tier-1.
# Unlike DQN, CQL is OFFLINE — parallel rollouts cannot speed it up;
# the wall time is 1600+ jitted updates on one core.
@pytest.mark.slow
@pytest.mark.timeout_s(500)
def test_cql_learns_pendulum_offline(ray_start_regular):
    """Run-to-reward OFFLINE: train CQL purely from a logged near-expert
    dataset (no env interaction during learning) and check the offline
    policy lands far above random and near the behavior policy."""
    from ray_tpu.rl import CQLConfig

    ds = _scripted_pendulum_dataset(n_episodes=30, noise=0.15, seed=7)
    assert ds.count() == 30 * 200

    cql = CQLConfig(env="Pendulum-v1", seed=7).training(
        updates_per_iteration=400, cql_alpha=10.0, bc_iters=1200).build(ds)
    for _ in range(4):
        m = cql.train()
    assert np.isfinite(m["critic_loss"])
    # Behavior mean ~ -160, random ~ -1200, untrained actor ~ -1400.
    # Measured: ~ -600..-700 after 1600 updates (BC warm start reaches
    # it; the conservative fine-tune HOLDS it — without the CQL term the
    # flat-Q entropy gradient diffuses the policy back to random).
    # XLA-CPU reduction order varies run-to-run under load, so the budget
    # is ADAPTIVE: train a bit more if the first eval misses the bar.
    best = cql.evaluate(num_episodes=5)["episode_return_mean"]
    for _extra in range(3):
        if best > -900.0:
            break
        cql.train()
        best = max(best, cql.evaluate(num_episodes=5)
                   ["episode_return_mean"])
    assert best > -900.0, best


# --------------------------------------------- connector pipelines (r5)
# Module-to-env action connectors + learner connectors (VERDICT r4 Weak #6
# / Next #9; reference: rllib/connectors/module_to_env/, connectors/learner/)


def test_action_connector_units():
    from ray_tpu.rl.connectors import ClipAction, RescaleAction, UnsquashAction

    uns = UnsquashAction(low=[-2.0], high=[2.0])
    out = uns(np.array([[-1.0], [0.0], [1.0], [3.0]]))  # 3.0 clips to 1
    assert np.allclose(out, [[-2.0], [0.0], [2.0], [2.0]])
    clip = ClipAction(low=[-0.5], high=[0.5])
    assert np.allclose(clip(np.array([[-2.0], [0.2]])), [[-0.5], [0.2]])
    res = RescaleAction(scale=2.0, shift=1.0)
    assert np.allclose(res(np.array([[1.0]])), [[3.0]])
    with pytest.raises(ValueError):
        UnsquashAction(low=[-np.inf], high=[np.inf])


def test_learner_connector_normalizes_advantages():
    from ray_tpu.rl.connectors import (NormalizeAdvantages,
                                       apply_learner_connectors)

    batch = {"advantages": np.array([1.0, 2.0, 3.0, 4.0], np.float32),
             "obs": np.zeros((4, 2))}
    out = apply_learner_connectors([NormalizeAdvantages()], batch)
    assert abs(float(out["advantages"].mean())) < 1e-6
    assert abs(float(out["advantages"].std()) - 1.0) < 1e-5
    assert out["obs"] is batch["obs"]  # other keys untouched
    # Original batch not mutated.
    assert batch["advantages"][0] == 1.0


class _RecordingActionConnector:
    """Test connector: counts calls, passes actions through."""

    def __init__(self):
        self.calls = 0
        self.last_min = None
        self.last_max = None

    def __call__(self, actions):
        self.calls += 1
        self.last_min = float(np.min(actions))
        self.last_max = float(np.max(actions))
        return actions


@pytest.mark.timeout_s(240)
def test_sac_runs_through_action_connector_chain(ray_start_regular):
    """SAC's continuous actions flow through an explicit module-to-env
    chain (unsquash to env bounds, then clip tighter) — structural
    continuous-control support, not per-policy rescale hacks."""
    from ray_tpu.rl import SACConfig
    from ray_tpu.rl.connectors import ClipAction, UnsquashAction

    algo = SACConfig(env="Pendulum-v1", seed=3, num_env_runners=1,
                     warmup_steps=64, updates_per_iteration=2).training(
        action_connectors=[UnsquashAction(low=[-2.0], high=[2.0]),
                           ClipAction(low=[-1.5], high=[1.5])]).build()
    try:
        m = algo.train()
        assert m["env_steps_this_iter"] > 0
        # Policy-space actions ([-1, 1]) are what the buffer stores; the
        # clip applies only on the env side.
        batch, _, _ = algo.buffer.sample(8)
        assert np.abs(batch["actions"]).max() <= 1.0 + 1e-6
    finally:
        algo.stop()


@pytest.mark.timeout_s(240)
def test_cql_evaluate_uses_action_connectors(ray_start_regular):
    """CQL's evaluation rollouts map actions through the connector chain
    (observable in-process: the recorder sees every step)."""
    from ray_tpu import data as rdata
    from ray_tpu.rl import CQLConfig
    from ray_tpu.rl.connectors import UnsquashAction

    rec = _RecordingActionConnector()
    n = 64
    ds = rdata.from_numpy({
        "obs": np.random.default_rng(0).normal(size=(n, 3)).astype(
            np.float32),
        "actions": np.zeros((n, 1), np.float32),
        "rewards": np.zeros(n, np.float32),
        "next_obs": np.zeros((n, 3), np.float32),
        "terminateds": np.zeros(n, np.float32),
    }, num_blocks=2)
    cql = CQLConfig(env="Pendulum-v1", seed=0).training(
        updates_per_iteration=2,
        action_connectors=[rec, UnsquashAction(low=[-2.0],
                                               high=[2.0])]).build(ds)
    cql.train()
    out = cql.evaluate(num_episodes=1)
    assert rec.calls >= 200  # one Pendulum episode = 200 steps
    assert "episode_return_mean" in out
    # Recorder saw POLICY-space actions (inside [-1, 1], pre-unsquash).
    assert -1.0 - 1e-6 <= rec.last_min and rec.last_max <= 1.0 + 1e-6
