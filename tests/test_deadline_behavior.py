"""Deadline-safety behavioral contract (ISSUE 20).

graftlint v5's first strict run flagged every literal control-plane RPC
that could park a thread forever on a lost reply; this file is the
behavioral half of those fixes. Each test installs a faultinject
``drop`` rule on the EXACT controller endpoint its subsystem calls —
the server eats the reply, exactly a lost-reply partition — and proves
the caller now surfaces the typed :class:`RpcTimeout` (or its
documented catch-path degraded result) within the configured bound,
where the pre-fix code hung until process death.

One module-scoped cluster (virtual 4-host slice, faultinject plumbed
in before init) shared by every test, same shape as
``test_multihost_group.py``.
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.core import multihost
from ray_tpu.core.config import config
from ray_tpu.core.multihost import GangPlacementError, HostGroup
from ray_tpu.core.rpc import RpcTimeout
from ray_tpu.core.runtime import get_core_worker
from ray_tpu.util import faultinject
from ray_tpu.util.deadline import Deadline
from ray_tpu.util.faultinject import Faults

_FAULTS = "/tmp/ray_tpu_deadline_faults.json"

# Every bounded-degradation assertion allows this much wall clock: the
# configured RPC bound (1-2s in these tests) plus generous CI slack.
# The point is the order-of-magnitude contrast with "forever".
_BOUND_S = 20.0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    saved = {k: os.environ.get(k)
             for k in ("RAY_TPU_VIRTUAL_SLICE", "RAY_TPU_FAULTINJECT_PATH")}
    os.environ["RAY_TPU_VIRTUAL_SLICE"] = "4x4/4"
    os.environ["RAY_TPU_FAULTINJECT_PATH"] = _FAULTS
    old_path = config.faultinject_path
    config.faultinject_path = _FAULTS
    faultinject.reset_counters()
    core = ray_tpu.init(num_cpus=8)
    yield core
    ray_tpu.shutdown()
    config.faultinject_path = old_path
    faultinject.reset_counters()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@pytest.fixture
def short_ctrl_timeout(cluster, monkeypatch):
    monkeypatch.setattr(config, "ctrl_call_timeout_s", 1.0)
    faultinject.reset_counters()
    yield
    faultinject.reset_counters()


def _reservations():
    from ray_tpu.core.placement import cluster_topology

    out = {}
    for s in cluster_topology()["slices"].values():
        out.update(s["reservations"])
    return out


# ------------------------------------------------ gang formation


def test_gang_formation_lost_reply_is_typed_refusal(cluster, monkeypatch):
    """A dropped ``mh_register_group`` reply mid-formation: the
    formation Deadline fires as RpcTimeout, the abort path releases the
    already-reserved sub-slice, and the caller gets the typed
    GangPlacementError — not a parked formation thread holding chips."""
    monkeypatch.setattr(config, "mh_form_timeout_s", 2.0)
    faultinject.reset_counters()
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.mh_register_group", "drop")
        with pytest.raises(GangPlacementError) as exc:
            HostGroup(2, name="dl-gang").start()
    assert time.monotonic() - t0 < _BOUND_S
    assert isinstance(exc.value.__cause__, RpcTimeout)
    # Release-once on the abort path still ran: no stranded chips.
    assert _reservations() == {}
    faultinject.reset_counters()


def test_registry_state_lost_reply_is_typed(short_ctrl_timeout):
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.mh_group_state", "drop")
        with pytest.raises(RpcTimeout):
            multihost.registry_state()
    assert time.monotonic() - t0 < _BOUND_S


def test_drop_gang_lost_reply_degrades_false(short_ctrl_timeout):
    """drop_gang is documented best-effort: the lost reply must come
    back as ``False`` within the bound, never a hang."""
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.mh_drop_group", "drop")
        assert multihost.drop_gang("no-such-group") is False
    assert time.monotonic() - t0 < _BOUND_S


# ------------------------------------------------ serve control plane


def test_serve_controller_membership_unknown_not_hung(short_ctrl_timeout):
    """The serve controller's node-membership probe: a lost list_nodes
    reply is the documented UNKNOWN (None) — the reconcile loop changes
    nothing — instead of wedging the reconcile thread."""
    from ray_tpu.serve.controller import ServeController

    sc = ServeController.__new__(ServeController)
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.list_nodes", "drop")
        assert sc._alive_nodes() is None
    assert time.monotonic() - t0 < _BOUND_S


def test_router_existence_probe_falls_through_bounded(short_ctrl_timeout):
    """The router's fail-fast existence probe must itself fail fast: a
    lost psub_snapshot reply degrades to "can't tell" (True -> normal
    wait path) within the bound."""
    from ray_tpu.serve.deployment import _Router

    r = _Router.__new__(_Router)
    r.name = "no-such-deployment"
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.psub_snapshot", "drop")
        assert r._known_to_controller() is True
    assert time.monotonic() - t0 < _BOUND_S


def test_serve_status_retry_runs_on_remaining_budget(monkeypatch):
    """status(timeout=T) is one budget for the WHOLE probe: the
    retry-once path must run on the REMAINING time, not a fresh T."""
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    seen = []

    class _H:
        class status:  # noqa: N801 - mimics a remote method handle
            @staticmethod
            def remote():
                return "ref"

    def fake_get(ref, timeout=None):
        seen.append(timeout)
        if len(seen) == 1:
            time.sleep(0.3)
            raise RuntimeError("first attempt burned 0.3s")
        return {}

    monkeypatch.setattr(ray_tpu, "get_actor", lambda name: _H())
    monkeypatch.setattr(serve_api, "_controller_alive", lambda h: True)
    monkeypatch.setattr(ray_tpu, "get", fake_get)
    assert serve.status(timeout=2.0, include_slo=False) == {}
    assert len(seen) == 2
    assert seen[0] == pytest.approx(2.0, abs=0.2)
    assert seen[1] < seen[0] - 0.25  # the 0.3s burn came OUT of it


# ------------------------------------------------ pipeline plane


def test_pipeline_registry_state_lost_reply_is_typed(short_ctrl_timeout):
    from ray_tpu.train.pipeline_plane import PipelinePlane

    plane = PipelinePlane.__new__(PipelinePlane)
    plane.name = "no-such-pipeline"
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.pipe_state", "drop")
        with pytest.raises(RpcTimeout):
            plane.registry_state()
    assert time.monotonic() - t0 < _BOUND_S


# ------------------------------------------------ autopilot


def test_autopilot_status_taints_degrade_bounded(short_ctrl_timeout):
    """Autopilot.status() against a head that eats taint_state replies:
    the taints panel degrades to {} within the bound — observability of
    the autopilot must not hang on the exact outage it watches for."""
    from ray_tpu.autopilot import Autopilot

    pilot = Autopilot(client=get_core_worker().controller)
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.taint_state", "drop")
        out = pilot.status()
    assert out["taints"] == {}
    assert time.monotonic() - t0 < _BOUND_S


# ------------------------------------------------ log streamer


def test_log_streamer_key_discovery_lost_reply_is_typed(cluster,
                                                        monkeypatch):
    """psub_keys was the streamer's ONE unbounded call (the long-polls
    were already bounded): a lost reply now raises RpcTimeout into the
    _loop's catch-and-backoff instead of parking the pump forever."""
    from ray_tpu.core import log_monitor
    from ray_tpu.core.log_monitor import LogStreamer

    monkeypatch.setattr(log_monitor, "_RPC_SLACK_S", 1.0)
    faultinject.reset_counters()
    streamer = LogStreamer.__new__(LogStreamer)
    streamer._controller = get_core_worker().controller
    streamer._seen = {}
    streamer._versions = {}
    streamer._stopped = threading.Event()
    t0 = time.monotonic()
    with Faults(_FAULTS) as f:
        f.add("rpc.server.controller.psub_keys", "drop")
        with pytest.raises(RpcTimeout):
            streamer.poll_once(window_s=0.2)
    assert time.monotonic() - t0 < _BOUND_S
    faultinject.reset_counters()


# ------------------------------------------------ Deadline helper


def test_deadline_unlimited_and_bounded():
    assert Deadline.after(None).remaining() is None
    assert not Deadline.after(None).expired
    dl = Deadline.after(5.0)
    r = dl.remaining()
    assert 0.0 < r <= 5.0
    assert not dl.expired


def test_deadline_expired_floors_not_forever():
    """An overdrawn budget must read as a tiny FINITE wait (so the
    typed timeout fires promptly), never as None/forever."""
    dl = Deadline(time.monotonic() - 1.0)
    assert dl.expired
    r = dl.remaining()
    assert r is not None and 0.0 < r <= 0.01


def test_deadline_child_capped_by_parent():
    parent = Deadline.after(10.0)
    child = parent.sub(2.0)
    assert child.remaining() <= 2.0
    capped = parent.sub(100.0)
    assert capped.remaining() <= parent.remaining() + 0.01
    assert Deadline.after(None).sub(3.0).remaining() <= 3.0
