"""graftlint v3 tests: static sharding/mesh safety, topology-lease
pairing, and the generated typed RPC stubs + drift gate.

Same layering as tests/test_analysis.py / test_analysis_v2.py:

1. Per-rule TP/TN fixtures — synthetic modules fed straight to the
   checkers (no jax, no cluster).
2. Mutation fixtures on the REAL repo sources: a contraction-dim
   partition injected into DECODE_RULES, a dropped constrain anchor,
   a dropped release on _add_replica's exception path, and a handler
   signature change without stub regeneration are each caught
   statically (the acceptance criteria — no jax import anywhere here).
3. Stub generation: determinism, the checked-in module is current, and
   stub call sites feed dead-endpoint/arity checking.
4. --diff coverage + speed for the new families; per-family repo-clean
   gates.
"""

import textwrap
import time

import pytest

from ray_tpu.analysis import repo_root, run_analysis
from ray_tpu.analysis import rules
from ray_tpu.analysis import lifetime, rpc_contract, sharding_safety, stubgen
from ray_tpu.analysis.callgraph import CallGraph
from ray_tpu.analysis.core import Project, SourceFile


def project_at(modules) -> Project:
    """Like test_analysis_v2.project_of, but keyed by repo-relative
    subpath ("parallel/sharding") so fixtures can land on the module
    names the rules tables point at."""
    files = []
    for sub, src in modules.items():
        rel = f"ray_tpu/{sub}.py"
        files.append(SourceFile(f"/fixture/{rel}", rel,
                                textwrap.dedent(src)))
    return Project("/fixture", files)


def run_checker(check, project):
    graph = CallGraph(project)
    findings = check(graph)
    by_rel = {f.relpath: f for f in project.files}
    return [f for f in findings
            if not by_rel[f.path].suppressed(f.rule, f.line)]


def repo_project_with(path, old, new) -> Project:
    """The real repo with ONE file's text patched — the mutation-fixture
    harness (nothing touches disk)."""
    project = Project.load(repo_root())
    files = []
    hit = False
    for f in project.files:
        if f.relpath == path:
            text = f.text.replace(old, new)
            assert text != f.text, f"mutation no-op in {path}: {old!r}"
            files.append(SourceFile(f.abspath, f.relpath, text))
            hit = True
        else:
            files.append(f)
    assert hit, path
    return Project(project.root, files)


# ------------------------------------------------- sharding fixtures

SHARD_RULES = """
    DECODE_RULES = {
        "batch": "batch",
        "length": None,
        "act_embed": None,
        "embed": None,
        "heads": "model",
        "head_dim": None,
        "mlp": "model",
        "attn_heads": None,
        "mlp_hidden": None,
    }
    DEFAULT_RULES = {
        "batch": ("data", "fsdp"),
        "length": "seq",
        "act_embed": None,
        "embed": "fsdp",
        "heads": "tensor",
        "head_dim": None,
        "mlp": "tensor",
        "attn_heads": "tensor",
        "mlp_hidden": "tensor",
    }
"""

SHARD_MODEL = """
    def param_axes():
        layers = {
            "wo": ("layers", "heads", "head_dim", "embed"),
            "w_down": ("layers", "mlp", "embed"),
            "wq": ("layers", "embed", "heads", "head_dim"),
        }
        return {"layers": layers}

    def decode_param_axes():
        axes = param_axes()
        layers = axes["layers"]
        layers["wo"] = ("layers", None, None, None)
        layers["w_down"] = ("layers", None, None)
        return axes

    def anchored_layer(x, layer, att):
        att = constrain(att, ("batch", "length", "attn_heads",
                              "head_dim"))
        out = jnp.einsum("bshd,hde->bse", att, layer["wo"])
        ffn = constrain(x, ("batch", "length", "mlp_hidden"))
        down = jnp.einsum("bsm,me->bse", ffn, layer["w_down"])
        return out + down

    def projection(h, layer):
        h = constrain(h, ("batch", "length", "act_embed"))
        return jnp.einsum("bse,ehd->bshd", h, layer["wq"])
"""


def shard_project(rules_src=SHARD_RULES, extra=None):
    mods = {"parallel/sharding": rules_src, "models/llama": SHARD_MODEL}
    if extra:
        mods.update(extra)
    return project_at(mods)


def test_sharding_clean_fixture():
    found = run_checker(sharding_safety.check, shard_project())
    assert found == [], [f.render() for f in found]


def test_sharding_partitioned_contraction_tp():
    # the anchor axis now maps to a mesh axis: the w_down reduction
    # splits across the mesh -> flagged at the einsum site
    bad = SHARD_RULES.replace('"mlp_hidden": None,\n    }',
                              '"mlp_hidden": "model",\n    }', 1)
    found = run_checker(sharding_safety.check, shard_project(bad))
    assert [f.rule for f in found] == [rules.SHARDING_CONTRACTION]
    assert "mlp_hidden" in found[0].message
    assert found[0].symbol == "anchored_layer"


def test_sharding_weight_side_contraction_tp():
    # dropping the decode override leaves wo sharded over heads — the
    # WEIGHT operand itself carries the partitioned contraction dim
    model = SHARD_MODEL.replace(
        '        layers["wo"] = ("layers", None, None, None)\n', "")
    found = run_checker(
        sharding_safety.check,
        project_at({"parallel/sharding": SHARD_RULES,
                    "models/llama": model}))
    assert any(f.rule == rules.SHARDING_CONTRACTION
               and "heads" in f.message for f in found), \
        [f.render() for f in found]


def test_sharding_missing_anchor_tp():
    model = SHARD_MODEL.replace(
        '        att = constrain(att, ("batch", "length", "attn_heads",\n'
        '                              "head_dim"))\n', "")
    found = run_checker(
        sharding_safety.check,
        project_at({"parallel/sharding": SHARD_RULES,
                    "models/llama": model}))
    assert [f.rule for f in found] == [rules.SHARDING_ANCHOR]
    assert "'wo'" in found[0].message


def test_sharding_output_dim_projection_is_tn():
    # wq shards its OUTPUT dims (heads over model) — contraction is
    # embed (replicated): no finding, sharding outputs is the point
    found = run_checker(sharding_safety.check, shard_project())
    assert not any(f.symbol == "projection" for f in found)


RULE3_SRC = """
    import jax
    from ray_tpu.parallel.sharding import axis_rules

    class Engine:
        def _mesh_scoped(self, fn):
            return fn

        def build(self, sh, kw):
            bad = self._mesh_scoped(jax.jit(self._impl))
            good = self._mesh_scoped(jax.jit(self._impl,
                                             out_shardings=sh))
            unknown = self._mesh_scoped(jax.jit(self._impl, **kw))
            return bad, good, unknown

        def commit(self, sh):
            with axis_rules(None, None):
                bad = jax.device_put(self.params)
                good = jax.device_put(self.params, sh)
            off_scope = jax.device_put(self.params)
            return bad, good, off_scope

        def _impl(self, x):
            return x
"""


def test_sharding_unpinned_mesh_call():
    found = run_checker(sharding_safety.check,
                        project_at({"serve/engine": RULE3_SRC}))
    by_rule = [f for f in found if f.rule == rules.SHARDING_UNPINNED]
    msgs = sorted(f.message.split(" ")[0] for f in by_rule)
    # exactly the unpinned jit in the wrapper and the placement-less
    # device_put INSIDE the scope; the **kw splat and off-scope
    # device_put are not flagged
    assert msgs == ["device_put", "jit"], [f.render() for f in found]


RULE4_SRC = """
    import jax
    from ray_tpu.parallel.sharding import axis_rules

    def sharded_body(x):
        return constrain(x, ("batch",))

    def plain_body(x):
        return x + 1

    def scoped_step(x):
        with axis_rules(None, None):
            return sharded_body(x)

    def build_bad(sh):
        return jax.jit(sharded_body, out_shardings=sh)

    def build_scoped(sh):
        with axis_rules(None, None):
            return jax.jit(sharded_body, out_shardings=sh)

    def build_selfscoped(sh):
        return jax.jit(scoped_step, out_shardings=sh)

    def build_plain(sh):
        return jax.jit(plain_body, out_shardings=sh)
"""


def test_sharding_unscoped_trace():
    found = run_checker(sharding_safety.check,
                        project_at({"parallel/builders": RULE4_SRC}))
    hits = [f for f in found if f.rule == rules.SHARDING_UNSCOPED]
    # only build_bad: jit-with-shardings of a constrain-reaching body,
    # outside any scope, body does not open the scope itself
    assert [f.symbol for f in hits] == ["build_bad"], \
        [f.render() for f in found]


# ------------------------------------------------- topology leases

LEASE_SRC = """
    class Controller:
        def leaky(self, client, rid):
            sub = client.call("reserve_subslice", rid, 4)
            self.spawn(sub["nodes"])
            self.record(sub)

        def guarded(self, client, rid):
            sub = client.call("reserve_subslice", rid, 4)
            if sub is None:
                self.log_refusal(rid)
                return False
            try:
                self.spawn(sub["nodes"])
            except Exception:
                client.call("release_subslice", sub["reservation_id"])
                raise
            self.record(sub)
            return True

        def released_via_helper(self, client, rid):
            sub = client.call("reserve_subslice", rid, 4)
            try:
                self.spawn(sub["nodes"])
            except Exception:
                self._drop_lease(sub["reservation_id"])
                raise
            self.record(sub)

        def settled_normally(self, client, rid):
            sub = client.call("reserve_subslice", rid, 4)
            self.record(sub)
            return True

        def _drop_lease(self, reservation_id):
            self.client.call("release_subslice", reservation_id)
"""


def test_lease_leak_on_exception_path():
    found = run_checker(lifetime.check,
                        project_at({"serve/ctl": LEASE_SRC}))
    assert [f.symbol for f in found] == ["Controller.leaky"]
    assert found[0].rule == rules.RESOURCE_LEAK
    assert "reserve_subslice" in found[0].message
    assert "escaping exception" in found[0].message


def test_lease_clean_idioms():
    """None-guard pruning, release in the handler (direct or through a
    self.-callee resolved over the call graph), bare-arg handoff, and
    a lease surviving a NORMAL exit (record-owned) are all clean."""
    found = run_checker(lifetime.check,
                        project_at({"serve/ctl": LEASE_SRC}))
    assert all(f.symbol == "Controller.leaky" for f in found), \
        [f.render() for f in found]


def test_lease_stub_spelling_recognized():
    src = """
        class Controller:
            def leaky(self, stub, rid):
                sub = stub.reserve_subslice(rid, 4)
                self.spawn(sub["nodes"])
                self.record(sub)

            def clean(self, stub, rid):
                sub = stub.reserve_subslice(rid, 4)
                try:
                    self.spawn(sub["nodes"])
                except Exception:
                    stub.release_subslice(sub["reservation_id"])
                    raise
                self.record(sub)
    """
    found = run_checker(lifetime.check, project_at({"serve/ctl": src}))
    assert [f.symbol for f in found] == ["Controller.leaky"]


# ------------------------------------------- mutation fixtures (repo)

def test_mutation_decode_rules_partition_caught():
    """Acceptance: a contraction-dim partition injected into the REAL
    DECODE_RULES is caught statically, no jax import."""
    project = repo_project_with(
        "ray_tpu/parallel/sharding.py",
        '"mlp_hidden": None,', '"mlp_hidden": "model",')
    found = run_checker(sharding_safety.check, project)
    hits = [f for f in found if f.rule == rules.SHARDING_CONTRACTION]
    assert hits, [f.render() for f in found]
    # fires at the real w_down reductions in the model code
    assert any(f.path == "ray_tpu/models/llama.py" for f in hits)
    assert any(f.path == "ray_tpu/models/llama_decode.py" for f in hits)


def test_mutation_dropped_anchor_caught():
    project = repo_project_with(
        "ray_tpu/models/llama_decode.py",
        '        att = att.reshape(B, 1, c.n_heads, c.head_dim)'
        '.astype(x.dtype)\n'
        '        att = constrain(att, ("batch", "length", "attn_heads",'
        ' "head_dim"))',
        '        att = att.reshape(B, 1, c.n_heads, c.head_dim)'
        '.astype(x.dtype)')
    found = run_checker(sharding_safety.check, project)
    hits = [f for f in found if f.rule == rules.SHARDING_ANCHOR]
    # the dropped line is shared verbatim by the contiguous and paged
    # decode steps: both wo reductions lose their anchor
    assert sorted({f.symbol for f in hits}) == [
        "decode_step.body", "paged_decode_step.body"], \
        [f.render() for f in found]


def test_mutation_verify_rules_partition_caught():
    """Acceptance: sharding the wo-contraction axis in the REAL
    DECODE_RULES is caught at the speculative verify forward too — the
    spec-mode verify program sits under the same bit-exactness
    contract as the decode steps."""
    project = repo_project_with(
        "ray_tpu/parallel/sharding.py",
        '"attn_heads": None,', '"attn_heads": "model",')
    found = run_checker(sharding_safety.check, project)
    hits = [f for f in found if f.rule == rules.SHARDING_CONTRACTION]
    assert hits, [f.render() for f in found]
    assert any(f.symbol == "paged_verify.body" for f in hits), \
        sorted({f.symbol for f in hits})


def test_mutation_verify_dropped_anchor_caught():
    """The S-shaped attention anchor line is shared verbatim by the
    contiguous suffix, paged suffix and spec verify forwards: dropping
    it loses the pre-wo anchor in all three."""
    project = repo_project_with(
        "ray_tpu/models/llama_decode.py",
        '        att = att.transpose(0, 3, 1, 2, 4).reshape(\n'
        '            B, S, c.n_heads, c.head_dim).astype(x.dtype)\n'
        '        att = constrain(att, ("batch", "length", "attn_heads",'
        ' "head_dim"))',
        '        att = att.transpose(0, 3, 1, 2, 4).reshape(\n'
        '            B, S, c.n_heads, c.head_dim).astype(x.dtype)')
    found = run_checker(sharding_safety.check, project)
    hits = [f for f in found if f.rule == rules.SHARDING_ANCHOR]
    assert sorted({f.symbol for f in hits}) == [
        "paged_prefill_suffix.body", "paged_verify.body",
        "prefill_suffix.body"], [f.render() for f in found]


def test_spec_programs_clean_under_decode_rules():
    """TN: the unmutated verify / draft / device-sampler programs carry
    their anchors and contract only unsharded axes — no sharding
    findings anywhere in the decode model module."""
    found = run_checker(sharding_safety.check,
                        Project.load(repo_root()))
    bad = [f for f in found
           if f.path == "ray_tpu/models/llama_decode.py"
           and f.rule in (rules.SHARDING_CONTRACTION,
                          rules.SHARDING_ANCHOR)]
    assert bad == [], "\n".join(f.render() for f in bad)


def test_mutation_dropped_lease_release_caught():
    """Acceptance: removing _add_replica's exception-path release is a
    repo-blocking finding (the reserve-then-spawn leak)."""
    project = repo_project_with(
        "ray_tpu/serve/controller.py",
        """        except Exception:
            if sub is not None:
                self._release_reservation(sub["reservation_id"],
                                          replica_id)
            raise""",
        """        except Exception:
            raise""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.RESOURCE_LEAK
            and f.symbol == "ServeController._add_replica"]
    assert len(hits) == 1, [f.render() for f in found]
    assert "reserve_subslice" in hits[0].message


def test_mutation_gang_dropped_subslice_release_caught():
    """Acceptance (ISSUE 13): HostGroup._form's partial-spawn cleanup
    must hand the sub-slice back on every exception path — removing
    the release from _abort_formation is the _add_replica leak shape
    at GANG granularity, and a repo-blocking finding."""
    project = repo_project_with(
        "ray_tpu/core/multihost.py",
        "            stub.release_subslice(reservation_id,\n"
        "                                  timeout=config.ctrl_call_timeout_s)\n",
        "            pass\n")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.RESOURCE_LEAK
            and f.symbol == "HostGroup._form"]
    assert len(hits) == 1, [f.render() for f in found]
    assert "reserve_subslice" in hits[0].message


def test_mutation_gang_dropped_group_drop_caught():
    """The mh_register_group -> mh_drop_group lease pair (rules
    extension): a partial spawn that stops dropping the half-created
    group record leaks it (and its fencing epoch) — caught statically
    through the _abort_formation self-callee chain."""
    project = repo_project_with(
        "ray_tpu/core/multihost.py",
        """            stub.mh_drop_group(self.group_id,
                               timeout=config.ctrl_call_timeout_s)
        except Exception:
            log_every("multihost.abort_drop\"""",
        """            pass
        except Exception:
            log_every("multihost.abort_drop\"""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.RESOURCE_LEAK
            and f.symbol == "HostGroup._form"]
    assert len(hits) == 1, [f.render() for f in found]
    assert "mh_register_group" in hits[0].message


def test_gang_lease_repo_clean():
    """TN: the real multihost module discharges both gang leases on
    every exception path (release through the _abort_formation
    self-callee, ownership handoff via _commit_formation)."""
    found = run_checker(lifetime.check, Project.load(repo_root()))
    assert [f for f in found
            if f.path == "ray_tpu/core/multihost.py"] == []


def test_mutation_dropped_checkpoint_save_caught():
    """Acceptance (PR 12): a state-mutating ServeController handler
    that stops reaching _save_state before returning is a repo-blocking
    finding — the mutation would be invisible to a restarted
    controller."""
    project = repo_project_with(
        "ray_tpu/serve/controller.py",
        """            self._routes[prefix] = name
        self._save_state()""",
        """            self._routes[prefix] = name""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.CHECKPOINT_MISSING]
    assert [f.symbol for f in hits] == ["ServeController.set_route"], \
        [f.render() for f in found]
    assert "_save_state" in hits[0].message


def test_mutation_deploy_checkpoint_not_discharged_by_callees():
    """deploy reaches _kill_replica, whose transitive _save_state lives
    on an EXCEPTION path (queued-release checkpoint) — that must not
    count as deploy having checkpointed: drop deploy's own save and the
    rule still fires."""
    project = repo_project_with(
        "ray_tpu/serve/controller.py",
        """        version = self._publish(rec)
        self._save_state()
        return version""",
        """        version = self._publish(rec)
        return version""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.CHECKPOINT_MISSING]
    assert [f.symbol for f in hits] == ["ServeController.deploy"], \
        [f.render() for f in found]


def test_checkpoint_discharged_via_self_callee_wrapper():
    """TN: routing the save through a self.-callee wrapper (the
    summary fixpoint's via-self hop) discharges the obligation."""
    project = repo_project_with(
        "ray_tpu/serve/controller.py",
        """            self._routes[prefix] = name
        self._save_state()""",
        """            self._routes[prefix] = name
        self._checkpoint_now()

    def _checkpoint_now(self):
        self._save_state()""")
    found = run_checker(lifetime.check, project)
    assert not [f for f in found if f.rule == rules.CHECKPOINT_MISSING
                and f.symbol == "ServeController.set_route"], \
        [f.render() for f in found]


def test_repo_clean_checkpoint_rule():
    """Every listed ServeController handler reaches _save_state today."""
    project = Project.load(repo_root())
    found = run_checker(lifetime.check, project)
    assert not [f for f in found
                if f.rule == rules.CHECKPOINT_MISSING], \
        [f.render() for f in found]


def test_mutation_handler_signature_drift_caught():
    """Acceptance: a handler signature change without --gen-stubs fails
    the drift gate."""
    project = repo_project_with(
        "ray_tpu/core/controller.py",
        "    def topology_state(self) -> Dict[str, Any]:",
        "    def topology_state(self, verbose: bool = False"
        ") -> Dict[str, Any]:")
    graph = CallGraph(project)
    found = stubgen.check(graph)
    assert [f.rule for f in found] == [rules.RPC_STUB_DRIFT]
    assert found[0].path == "ray_tpu/core/rpc_stubs.py"


# ------------------------------------------------- generated stubs

@pytest.mark.slow  # 9s: double full-repo stub gen; drift stays gated
# via test_repo_clean_rpc_stubs + make lint's stubs-check; PR 18 rebudget
def test_stub_generation_deterministic_and_current():
    project = Project.load(repo_root())
    a = stubgen.generate(CallGraph(project))
    b = stubgen.generate(CallGraph(Project.load(repo_root())))
    assert a == b
    on_disk = project.by_module["ray_tpu.core.rpc_stubs"].text
    assert a == on_disk, "stubs drifted: run --gen-stubs"


def test_stub_module_importable_and_trims_unset():
    from ray_tpu.core.rpc_stubs import ControllerStub, NodeStub, _UNSET

    calls = []

    class FakeClient:
        def call(self, method, *args, **kwargs):
            calls.append((method, args, kwargs))
            return "ok"

    stub = ControllerStub(FakeClient())
    assert stub.reserve_subslice("owner", 4) == "ok"
    method, args, kwargs = calls[-1]
    assert method == "reserve_subslice"
    assert args == ("owner", 4)
    assert kwargs == {}  # omitted optionals never hit the wire
    stub.reserve_subslice("owner", 4, [2, 2], timeout=5.0)
    method, args, kwargs = calls[-1]
    assert kwargs == {"shape": [2, 2], "timeout": 5.0}
    # required-arity errors fail AT THE CALL SITE, in Python
    with pytest.raises(TypeError):
        stub.reserve_subslice("owner")
    NodeStub(FakeClient()).kill_worker(b"wid", True, timeout=2.0)
    method, args, kwargs = calls[-1]
    assert (method, args) == ("kill_worker", (b"wid",))
    assert kwargs == {"force": True, "timeout": 2.0}
    assert _UNSET is not None


STUB_CONTRACT_FIXTURE = {
    "core/rpc_stubs": """
        _UNSET = object()

        class _StubBase:
            def __init__(self, client):
                self._client = client

            def _call(self, method, *args, timeout=_UNSET, **kwargs):
                return self._client.call(method, *args, **kwargs)

        class ControllerStub(_StubBase):
            def echo(self, x, *, timeout=_UNSET):
                return self._call('echo', x, timeout=timeout)

            def dead_one(self, *, timeout=_UNSET):
                return self._call('dead_one', timeout=timeout)
    """,
    "core/ctl": """
        class Controller:
            def __init__(self):
                self._srv = RpcServer(handlers={
                    "echo": self.echo,
                    "dead_one": self.dead,
                })

            def echo(self, x):
                return x

            def dead(self):
                return None

        class RpcServer:
            def __init__(self, handlers):
                self.handlers = handlers
    """,
    "user": """
        from ray_tpu.core.rpc_stubs import ControllerStub

        def chained(client):
            return ControllerStub(client).echo(1)

        def aliased(client):
            st = ControllerStub(client)
            return st.echo(1, 2)
    """,
}


def test_stub_sites_feed_contract_checking():
    found = run_checker(rpc_contract.check,
                        project_at(STUB_CONTRACT_FIXTURE))
    # echo is alive through stub sites (chained + aliased receivers);
    # dead_one's only literal spelling is the stub's own forwarding,
    # which must NOT count — it stays dead
    dead = [f for f in found if f.rule == rules.RPC_DEAD]
    assert [f.message.split('"')[1] for f in dead] == ["dead_one"]
    # the aliased site passes 2 args to a 1-arg handler: arity finding
    # AT the stub call site
    arity = [f for f in found if f.rule == rules.RPC_ARITY]
    assert len(arity) == 1 and arity[0].symbol == "aliased"


def test_gen_stubs_cli(tmp_path, capsys):
    from ray_tpu.analysis.__main__ import main

    out = tmp_path / "stubs.py"
    assert main(["--gen-stubs", str(out)]) == 0
    capsys.readouterr()
    disk = open(repo_root() + "/ray_tpu/core/rpc_stubs.py").read()
    assert out.read_text() == disk


# ------------------------------------------- --diff + speed coverage

def test_diff_mode_covers_new_families():
    """emit_files-restricted runs keep whole-program indexes (the rule
    tables and handler index span the package) and still surface
    findings in the changed file."""
    project = repo_project_with(
        "ray_tpu/parallel/sharding.py",
        '"mlp_hidden": None,', '"mlp_hidden": "model",')
    graph = CallGraph(project)
    # the mutation is in sharding.py but fires at model call sites:
    # a diff slice containing the MODEL file reports it
    found = sharding_safety.check(
        graph, emit_files={"ray_tpu/models/llama_decode.py"})
    assert found and all(f.path == "ray_tpu/models/llama_decode.py"
                         for f in found)
    # stub drift emits only when the stub module is in the slice
    drift_project = repo_project_with(
        "ray_tpu/core/controller.py",
        "    def topology_state(self) -> Dict[str, Any]:",
        "    def topology_state(self, verbose: bool = False"
        ") -> Dict[str, Any]:")
    g2 = CallGraph(drift_project)
    assert stubgen.check(g2, emit_files={"ray_tpu/core/rpc.py"}) == []
    assert stubgen.check(
        g2, emit_files={"ray_tpu/core/rpc_stubs.py"}) != []


def test_diff_one_file_stays_fast():
    """Speed gate extension: a one-file --diff run with ALL families
    (indexes still whole-program) stays fast. Budget recalibrated in
    PR 14 (152 files, standalone ~2.4 s -> 7 s) and again in PR 17:
    the package grew to 154 files incl. the disaggregated-serving
    splice plane and this box now measures standalone ~4.8 s, so 12 s
    keeps the original ~2.5x slack for a loaded CI box (same policy
    as test_full_run_is_fast; the tier-1 suite runs this gate
    mid-suite under heavy contention — the 7 s budget failed there at
    7.6 s while standalone stayed well under)."""
    t0 = time.perf_counter()
    findings, _ = run_analysis(
        emit_files={"ray_tpu/serve/controller.py"})
    elapsed = time.perf_counter() - t0
    assert findings == [], "\n".join(f.render() for f in findings)
    assert elapsed < 12.0, elapsed


# --------------------------------------- per-family repo-clean gates

def _clean_under(select):
    from ray_tpu.analysis import Baseline, DEFAULT_BASELINE

    findings, _ = run_analysis(select=select)
    baseline = Baseline.load(DEFAULT_BASELINE)
    new, _baselined, _stale = baseline.split(findings)
    return new


def test_repo_clean_sharding_safety():
    new = _clean_under([rules.SHARDING_CONTRACTION,
                        rules.SHARDING_ANCHOR,
                        rules.SHARDING_UNPINNED,
                        rules.SHARDING_UNSCOPED])
    assert new == [], "\n".join(f.render() for f in new)


def test_repo_clean_rpc_stubs():
    new = _clean_under([rules.RPC_STUB_DRIFT])
    assert new == [], "\n".join(f.render() for f in new)


def test_sharding_tables_actually_parsed():
    """Collector-liveness guard: if table parsing silently broke, the
    contraction rule would go quiet instead of loud."""
    project = Project.load(repo_root())
    tables = sharding_safety.load_rule_tables(project)
    assert set(rules.SHARDING_BITEXACT_TABLES) <= set(tables)
    decode = tables["DECODE_RULES"][0]
    assert decode["attn_heads"] is None and decode["mlp_hidden"] is None
    train, dec = sharding_safety.load_param_axes(project)
    row_par = sharding_safety.row_parallel_weights(
        train, dec, tables[rules.SHARDING_TRAIN_TABLE][0])
    assert row_par == {"wo", "w_down"}


def test_stub_groups_cover_all_servers():
    graph = CallGraph(Project.load(repo_root()))
    groups = stubgen.stub_groups(graph)
    assert {"Controller", "Node", "CoreWorker",
            "ClientServer"} <= set(groups)
    ctl = dict(groups["Controller"])
    assert "reserve_subslice" in ctl and "release_subslice" in ctl


# ---------------------------------------- PR 14: pipeline-plane idioms


def test_borrow_ref_pair_tp_tn():
    """The RESOURCE_METHOD_PAIRS borrow_ref -> drop_ref extension: a
    borrowed activation descriptor surviving an escaping exception is
    flagged; the finally-discharged twin is clean."""
    src = """
        class Stage:
            def leaky(self, desc):
                self._ledger.borrow_ref(desc)
                value = self.pull(desc)
                self._ledger.drop_ref(desc)
                return value

            def clean(self, desc):
                self._ledger.borrow_ref(desc)
                try:
                    return self.pull(desc)
                finally:
                    self._ledger.drop_ref(desc)
    """
    found = run_checker(lifetime.check,
                        project_at({"train/pipe_fix": src}))
    assert [f.symbol for f in found] == ["Stage.leaky"]
    assert "borrow_ref" in found[0].message


def test_mutation_stage_pull_dropped_release_caught():
    """Acceptance (ISSUE 14): turning StageActor._pull's finally-drop
    into a straight-line drop leaves the activation ref live across
    the fallible object-plane get — the _add_replica leak shape for
    ObjectRefs, caught statically."""
    project = repo_project_with(
        "ray_tpu/train/pipeline_plane.py",
        """        ref = self._ledger.borrow_ref(desc)
        try:
            return jnp.asarray(ray_tpu.get(ref, timeout=60.0))
        finally:
            self._ledger.drop_ref(desc)""",
        """        ref = self._ledger.borrow_ref(desc)
        out = jnp.asarray(ray_tpu.get(ref, timeout=60.0))
        self._ledger.drop_ref(desc)
        return out""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.RESOURCE_LEAK
            and f.symbol == "StageActor._pull"]
    assert len(hits) == 1, [f.render() for f in found]
    assert "borrow_ref" in hits[0].message


def test_mutation_pipeline_record_drop_caught():
    """The pipe_register -> pipe_drop lease pair: a formation abort
    that stops dropping the half-created pipeline record leaks it (and
    its fencing epoch) — caught through the _abort_formation
    self-callee chain."""
    project = repo_project_with(
        "ray_tpu/train/pipeline_plane.py",
        """            stub.pipe_drop(self.name, timeout=_cfg.ctrl_call_timeout_s)
        except Exception:
            log_every("pipeline.abort_drop\"""",
        """            pass
        except Exception:
            log_every("pipeline.abort_drop\"""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.RESOURCE_LEAK
            and f.symbol == "PipelinePlane._form_record"]
    assert len(hits) == 1, [f.render() for f in found]
    assert "pipe_register" in hits[0].message


def test_pipeline_plane_lifetime_repo_clean():
    """TN: the real pipeline plane discharges every activation ref and
    the pipeline record on every exception path."""
    found = run_checker(lifetime.check, Project.load(repo_root()))
    assert [f for f in found
            if f.path == "ray_tpu/train/pipeline_plane.py"] == []


def test_mutation_zero1_rules_partition_caught():
    """Acceptance (ISSUE 14): editing ZERO1_STATE_RULES to shard a
    MODEL axis over the data axis would partition contraction dims of
    the traced step — caught statically at the real einsum sites, no
    jax import."""
    project = repo_project_with(
        "ray_tpu/parallel/sharding.py",
        """ZERO1_STATE_RULES: Rules = {
    "zero1_shard": "data",
}""",
        """ZERO1_STATE_RULES: Rules = {
    "zero1_shard": "data",
    "embed": "data",
}""")
    found = run_checker(sharding_safety.check, project)
    hits = [f for f in found if f.rule == rules.SHARDING_CONTRACTION
            and "ZERO1_STATE_RULES" in f.message]
    assert hits, [f.render() for f in found]
    assert any(f.path == "ray_tpu/models/llama.py" for f in hits)


def test_zero1_table_parsed_and_state_only():
    """Collector-liveness guard for the ZeRO-1 table: it parses, maps
    the state-only axis to the data mesh axis, and names NO model
    axis (the property the mutation above breaks)."""
    project = Project.load(repo_root())
    tables = sharding_safety.load_rule_tables(project)
    z1 = tables["ZERO1_STATE_RULES"][0]
    assert z1 == {"zero1_shard": "data"}


# ----------------------------- PR 17: KV-page handoff lease (disagg)


def test_publish_handoff_pair_tp_tn():
    """The RESOURCE_METHOD_PAIRS publish_handoff -> discharge_handoff
    extension: a published handoff surviving an escaping exception is
    flagged; the guarded twin is clean — INCLUDING its normal exit,
    where the live lease is the design (the returned descriptor
    transfers the discharge obligation to the router splice)."""
    src = """
        class Prefill:
            def leaky(self, desc):
                self._handoffs.publish_handoff(desc)
                self.observe(desc)
                self._handoffs.discharge_handoff(desc["handoff_id"])

            def clean(self, desc):
                self._handoffs.publish_handoff(desc)
                try:
                    self.observe(desc)
                except BaseException:
                    self._handoffs.discharge_handoff(
                        desc["handoff_id"])
                    raise
                return desc
    """
    found = run_checker(lifetime.check,
                        project_at({"serve/handoff_fix": src}))
    assert [f.symbol for f in found] == ["Prefill.leaky"]
    assert "publish_handoff" in found[0].message


def test_mutation_prefill_handoff_dropped_discharge_caught():
    """Acceptance (ISSUE 17): un-guarding prefill_handoff's publish
    tail leaves the lease live across the fallible metrics observation
    — the refs (and the pinned KV pages behind them) leak on a raise
    until the TTL sweep. Caught statically through the _drop_handoff
    self-callee chain."""
    project = repo_project_with(
        "ray_tpu/serve/decode.py",
        """        self._handoffs.publish_handoff(desc)
        try:
            self._observe_handoff_published(desc)
        except BaseException:
            # The lease must not outlive a failed publish tail: hand the
            # refs back before the error escapes (graftlint polices the
            # publish->discharge pairing on every raise exit).
            self._drop_handoff(desc["handoff_id"], "aborted")
            raise
        return desc""",
        """        self._handoffs.publish_handoff(desc)
        self._observe_handoff_published(desc)
        self._drop_handoff(desc["handoff_id"], "aborted")
        return desc""")
    found = run_checker(lifetime.check, project)
    hits = [f for f in found if f.rule == rules.RESOURCE_LEAK
            and f.symbol == "LlamaDecodeDeployment.prefill_handoff"]
    assert len(hits) == 1, [f.render() for f in found]
    assert "publish_handoff" in hits[0].message


def test_handoff_lifetime_repo_clean():
    """TN: the real handoff plumbing (publish/adopt/abort/sweep across
    decode.py and deployment.py) discharges the lease on every
    exception path."""
    found = run_checker(lifetime.check, Project.load(repo_root()))
    assert [f for f in found
            if f.path in ("ray_tpu/serve/decode.py",
                          "ray_tpu/serve/deployment.py",
                          "ray_tpu/serve/handoff.py")] == []


# ------------------------------------- PR 18: autopilot action idiom


def _run_autopilot_lint(project):
    from ray_tpu.analysis import autopilot_lint

    findings = autopilot_lint.check_project(project)
    by_rel = {f.relpath: f for f in project.files}
    return [f for f in findings
            if not by_rel[f.path].suppressed(f.rule, f.line)]


def test_autopilot_unpaired_action_tp():
    """TP: an _act_* handler missing the fence, the audit, or both is
    flagged with the missing call(s) named."""
    project = project_at({"autopilot": """
        class Autopilot:
            def _act_no_audit(self, finding, epoch):
                if not self._fence_ok("taint-host", True):
                    return None
                return self._do_it(finding)

            def _act_no_fence(self, finding, epoch):
                return self._audit(finding, "shed-tenant", "d",
                                   "applied")

            def _act_neither(self, finding, epoch):
                return self._do_it(finding)
        """})
    findings = _run_autopilot_lint(project)
    assert len(findings) == 3
    assert all(f.rule == rules.AUTOPILOT_UNPAIRED for f in findings)
    by_sym = {f.symbol.rsplit(".", 1)[-1]: f.message for f in findings}
    assert "_audit" in by_sym["_act_no_audit"]
    assert "_fence_ok" in by_sym["_act_no_fence"]
    assert "_fence_ok" in by_sym["_act_neither"] \
        and "_audit" in by_sym["_act_neither"]


def test_autopilot_unpaired_action_tn():
    """TN: paired handlers pass; helper methods without the action
    prefix, module-level _act_-named functions (no class = not a
    handler), other modules, and a pragma'd site are all quiet."""
    project = project_at({"autopilot": """
        class Autopilot:
            def _act_good(self, finding, epoch):
                if not self._fence_ok("reschedule-gang", True):
                    return self._audit(finding, "reschedule-gang",
                                       "g", "stale-epoch")
                return self._audit(finding, "reschedule-gang", "g",
                                   "applied")

            def _decide(self, finding):
                return self._handlers["taint-host"](finding)

            # graftlint: disable=autopilot-unpaired-action (test fixture)
            def _act_pragma(self, finding, epoch):
                return None

        def _act_free_function(finding):
            return None
        """, "other_module": """
        class NotTheAutopilot:
            def _act_elsewhere(self, finding):
                return None
        """})
    assert _run_autopilot_lint(project) == []


def test_mutation_autopilot_dropped_fence_caught():
    """Mutation fixture: neutering the resize handler's fence check in
    the REAL autopilot.py is caught statically."""
    project = repo_project_with(
        "ray_tpu/autopilot.py",
        'if not self._fence_ok("resize-deployment",',
        'if not (lambda *_a: True)("resize-deployment",')
    findings = _run_autopilot_lint(project)
    hits = [f for f in findings
            if f.symbol.endswith("_act_resize_deployment")]
    assert len(hits) == 1, [f.render() for f in findings]
    assert "_fence_ok" in hits[0].message


def test_repo_clean_autopilot():
    new = _clean_under([rules.AUTOPILOT_UNPAIRED])
    assert new == [], "\n".join(f.render() for f in new)
