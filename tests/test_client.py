"""Thin-client protocol tests (reference: Ray Client, util/client/).

The client process owns nothing: a ClientServer inside the cluster hosts
the real refs/actors. Covered: put/get, tasks with (nested) ref args,
multiple returns, actors incl. named lookup + kill, wait, disconnect
cleanup semantics, and a REAL separate client process driving the cluster
over one TCP connection.
"""

import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest


@pytest.fixture
def client_pair(ray_start_regular):
    from ray_tpu import client as client_mod

    server = client_mod.ClientServer(host="127.0.0.1")
    client = client_mod.connect(f"ray-tpu://{server.address[0]}:"
                                f"{server.address[1]}")
    yield server, client
    client.disconnect()
    server.stop()


def test_put_get_task_actor_roundtrip(client_pair):
    import ray_tpu

    _server, client = client_pair

    # put/get with numpy payload
    arr = np.arange(1000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)

    # top-level ref arg: resolved to its value before execution
    @ray_tpu.remote
    def add(a, b):
        return a + b

    out = ray_tpu.get(add.remote(ref, np.ones(1000, np.float32)))
    np.testing.assert_array_equal(out, arr + 1.0)

    # NESTED ref (reference semantics: stays a ref; the task gets it)
    @ray_tpu.remote
    def nested_sum(d):
        return float(ray_tpu.get(d["r"]).sum()) + d["c"]

    assert ray_tpu.get(nested_sum.remote({"r": ref, "c": 0.5})) == \
        float(arr.sum()) + 0.5

    # multiple returns
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2

    r1, r2 = two.remote()
    assert ray_tpu.get(r1) == 1 and ray_tpu.get(r2) == 2

    # wait
    ready, pending = ray_tpu.wait([r1, r2], num_returns=2, timeout=30)
    assert len(ready) == 2 and not pending

    # actor create/call/kill
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote()) == 11
    assert ray_tpu.get(c.inc.remote(5)) == 16
    ray_tpu.kill(c)

    # task error propagates to the client
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("boom-from-task")

    with pytest.raises(Exception, match="boom"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_named_actor_survives_disconnect(client_pair):
    import ray_tpu
    from ray_tpu import client as client_mod

    server, client = client_pair

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.v = 7

        def v_(self):
            return self.v

    named = Holder.options(name="keeper").remote()
    unnamed = Holder.remote()
    assert ray_tpu.get(named.v_.remote()) == 7
    unnamed_key = unnamed._key
    client.disconnect()

    # Reconnect: the named actor is still there, the unnamed one is gone.
    client2 = client_mod.connect(
        f"ray-tpu://{server.address[0]}:{server.address[1]}")
    try:
        again = ray_tpu.get_actor("keeper")
        assert ray_tpu.get(again.v_.remote()) == 7
        with pytest.raises(Exception):
            h = client_mod.ClientActorHandle(unnamed_key, client2)
            ray_tpu.get(h.v_.remote(), timeout=15)
    finally:
        client2.disconnect()


@pytest.mark.slow  # PR 20 rebudget (5.1s): reap soak rides the
# session-GC timer; disconnect/reconnect behavior stays tier-1
@pytest.mark.timeout_s(120)
def test_stale_session_reaped(ray_start_regular):
    """A crashed client (keepalive stops, no disconnect) gets its session
    reaped server-side: refs released, unnamed actors killed."""
    from ray_tpu import client as client_mod
    from ray_tpu.core.config import config

    config.update({"client_session_timeout_s": 3.0})
    server = client_mod.ClientServer(host="127.0.0.1")
    client = client_mod.connect(
        f"ray-tpu://{server.address[0]}:{server.address[1]}")
    try:
        import ray_tpu

        @ray_tpu.remote
        class Doomed:
            def alive(self):
                return True

        d = Doomed.remote()
        assert ray_tpu.get(d.alive.remote(), timeout=60)
        assert len(server._sessions) == 1
        # Simulate a crash: keepalive stops, no disconnect ever arrives.
        client._stop_ping.set()
        deadline = time.monotonic() + 30
        while server._sessions and time.monotonic() < deadline:
            time.sleep(0.2)
        assert not server._sessions, "stale session was not reaped"
    finally:
        client.disconnect()
        server.stop()
        config.update({"client_session_timeout_s": 60.0})


@pytest.mark.timeout_s(150)
def test_separate_client_process(ray_start_regular):
    """A genuinely separate OS process drives the cluster as a thin client
    over one outbound TCP connection."""
    from ray_tpu import client as client_mod

    server = client_mod.ClientServer(host="127.0.0.1")
    script = textwrap.dedent(f"""
        import numpy as np
        import ray_tpu

        ray_tpu.init(address="ray-tpu://{server.address[0]}:{server.address[1]}")

        @ray_tpu.remote
        def square(x):
            return x * x

        refs = [square.remote(i) for i in range(8)]
        assert ray_tpu.get(refs, timeout=90) == [i * i for i in range(8)]

        @ray_tpu.remote
        class Acc:
            def __init__(self):
                self.total = 0
            def add(self, v):
                self.total += v
                return self.total

        acc = Acc.remote()
        for i in range(5):
            last = acc.add.remote(i)
        assert ray_tpu.get(last, timeout=60) == 10
        big = ray_tpu.put(np.ones((256, 256)))
        assert float(ray_tpu.get(big).sum()) == 256 * 256
        ray_tpu.shutdown()
        print("CLIENT-OK")
    """)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=120, env={**__import__("os").environ,
                              "PYTHONPATH": "/root/repo",
                              "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "CLIENT-OK" in proc.stdout
    finally:
        server.stop()
