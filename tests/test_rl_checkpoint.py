"""Algorithm checkpoint/restore + RL-under-Tune (VERDICT r4 Missing #3:
reference ``Algorithm`` is a Trainable with save/load_checkpoint —
``rllib/algorithms/algorithm.py:214``, ``tune/trainable/trainable.py:852``).
Kill-and-resume: the original algorithm (and its runner fleet) is fully
stopped before a fresh build restores the checkpoint."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import DQNConfig, PPOConfig, as_trainable


def _tree_equal(a, b) -> bool:
    import jax

    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.allclose(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b))


@pytest.mark.timeout_s(240)
def test_ppo_kill_and_resume(ray_start_regular, tmp_path):
    cfg = PPOConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=16, minibatch_size=32, num_sgd_epochs=1, seed=1)
    algo = cfg.build()
    try:
        for _ in range(2):
            algo.train()
        saved_params = algo.params
        algo.save(str(tmp_path / "ckpt"))
    finally:
        algo.stop()

    # "Crash": the first algorithm and its runners are gone. Rebuild and
    # restore — training continues from iteration 2 with identical params.
    algo2 = PPOConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=16, minibatch_size=32, num_sgd_epochs=1,
        seed=99).build()  # different seed: state must come from the ckpt
    try:
        algo2.restore(str(tmp_path / "ckpt"))
        assert algo2._iteration == 2
        assert _tree_equal(algo2.params, saved_params)
        m = algo2.train()
        assert m["training_iteration"] == 3
        assert m["env_steps_total"] > 0
    finally:
        algo2.stop()


@pytest.mark.timeout_s(240)
def test_dqn_kill_and_resume_with_replay_tail(ray_start_regular, tmp_path):
    cfg = DQNConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=32, learning_starts=32, batch_size=32,
        train_batches_per_iter=4, seed=1)
    algo = cfg.build()
    try:
        for _ in range(3):
            algo.train()
        saved_steps = algo._total_env_steps
        saved_learner_steps = algo._learner_steps
        saved_buffer_len = len(algo.buffer)
        saved_target = algo.target_params
        assert saved_buffer_len > 0
        algo.save(str(tmp_path / "ckpt"))
    finally:
        algo.stop()

    algo2 = DQNConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=32, learning_starts=32, batch_size=32,
        train_batches_per_iter=4, seed=7).build()
    try:
        algo2.restore(str(tmp_path / "ckpt"))
        assert algo2._iteration == 3
        assert algo2._total_env_steps == saved_steps
        assert algo2._learner_steps == saved_learner_steps
        # Replay tail restored (counts match exactly while under the tail
        # cap), and the target network is the saved one, not a fresh init.
        assert len(algo2.buffer) == saved_buffer_len
        assert _tree_equal(algo2.target_params, saved_target)
        m = algo2.train()
        assert m["training_iteration"] == 4
        assert m["buffer_size"] > saved_buffer_len
    finally:
        algo2.stop()


@pytest.mark.timeout_s(240)
def test_connector_state_survives_checkpoint(ray_start_regular, tmp_path):
    from ray_tpu.rl.connectors import NormalizeObs

    cfg = PPOConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=16, minibatch_size=32, num_sgd_epochs=1, seed=2,
        obs_connectors=[NormalizeObs()])
    algo = cfg.build()
    try:
        algo.train()
        conns = ray_tpu.get(algo.runners[0].get_connectors.remote())
        count_before = conns[0].count
        assert count_before > 0  # the runner's normalizer saw batches
        algo.save(str(tmp_path / "ckpt"))
    finally:
        algo.stop()

    algo2 = PPOConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=16, minibatch_size=32, num_sgd_epochs=1, seed=2,
        obs_connectors=[NormalizeObs()]).build()
    try:
        algo2.restore(str(tmp_path / "ckpt"))
        conns2 = ray_tpu.get(algo2.runners[0].get_connectors.remote())
        # Fresh build starts at count 0 (+probe); restore brings back the
        # saved running statistics.
        assert conns2[0].count >= count_before
        assert np.all(np.isfinite(conns2[0].mean))
    finally:
        algo2.stop()


@pytest.mark.timeout_s(300)
@pytest.mark.slow  # 8s: full ASHA sweep; kill/resume checkpoint
# tests stay in tier-1 (PR 16 rebudget)
def test_ppo_lr_sweep_under_asha(ray_start_regular):
    """RL-under-Tune: an Algorithm config as a Tune trainable, swept by
    ASHA (reference: any RLlib algorithm under ``Tuner``)."""
    from ray_tpu import tune
    from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner

    base = PPOConfig().environment("CartPole-v1").env_runners(
        1, num_envs_per_runner=2).training(
        rollout_length=16, minibatch_size=32, num_sgd_epochs=1, seed=3)
    tuner = Tuner(
        as_trainable(base, stop_iters=3),
        param_space={"lr": tune.grid_search([3e-4, 1e-3])},
        tune_config=TuneConfig(
            metric="total_loss", mode="min",
            scheduler=ASHAScheduler(metric="total_loss", mode="min",
                                    max_t=3, grace_period=1),
            max_concurrent_trials=2),
        resources_per_trial={"CPU": 1.0},
    )
    grid = tuner.fit()
    assert len(grid) == 2
    done = [r for r in grid if r.metrics and not r.error]
    assert done, [r.error for r in grid]
    assert all("total_loss" in r.metrics for r in done)
