"""Control-plane fault tolerance (ISSUE 12).

The serve controller's death is a NON-EVENT: its state (deployments,
replica ids + sub-slice reservations, routes, proxies, pending
releases) checkpoints through the core KV on every mutating op, a
restarted controller ADOPTS still-alive replicas by pinging their
handles (no respawn, no cold prefill, no double-reserved chips), an
epoch lease fences the zombie predecessor's writes, and the data plane
(routers, proxies, `serve.status`) keeps serving from cached snapshots
while the controller is down.

All fault scenarios drive through `util/faultinject.py` — the
deterministic, config-gated injection harness this PR introduces —
never ad-hoc `os.kill` monkeypatching.
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import config
from ray_tpu.serve.controller import (EPOCH_NAME, STATE_KEY,
                                      ServeController)
from ray_tpu.util import faultinject
from ray_tpu.util.faultinject import FaultInjected, Faults
from ray_tpu.util.metrics import _Registry


def _agg(source="n1/node/pid1"):
    """This process's registry as a one-source cluster aggregation."""
    return {source: _Registry.get().snapshot()}


# ------------------------------------------------ faultinject harness


@pytest.fixture
def faults_file(tmp_path, monkeypatch):
    path = str(tmp_path / "faults.json")
    monkeypatch.setattr(config, "faultinject_path", path)
    faultinject.reset_counters()
    yield path
    faultinject.reset_counters()


def test_faultinject_disabled_is_noop(monkeypatch):
    monkeypatch.setattr(config, "faultinject_path", "")
    faultinject.check("any.site.at.all")  # must not raise or stat


def test_faultinject_error_delay_counters(faults_file):
    with Faults(faults_file) as f:
        f.add("plane.op", "error", after=1, times=2)
        faultinject.check("plane.op")  # skipped: after=1
        with pytest.raises(FaultInjected):
            faultinject.check("plane.op")
        with pytest.raises(FaultInjected):
            faultinject.check("plane.op")
        faultinject.check("plane.op")  # times exhausted
        # glob sites + delay action
        f.add("rpc.server.*.slowme", "delay", delay_s=0.15)
        t0 = time.monotonic()
        faultinject.check("rpc.server.controller.slowme")
        assert time.monotonic() - t0 >= 0.14
        faultinject.check("rpc.server.controller.other")  # no match
    # context exit cleared the file: nothing fires any more
    faultinject.check("plane.op")
    faultinject.check("rpc.server.controller.slowme")


def test_faultinject_once_global_fuse(faults_file):
    with Faults(faults_file) as f:
        rule = f.add("fuse.site", "error", once_global=True,
                     rule_id="fuse-test")
        assert not f.marker_fired(rule)
        with pytest.raises(FaultInjected):
            faultinject.check("fuse.site")
        assert f.marker_fired(rule)
        # The cross-process fuse blew: no process fires it again, even
        # though this process's counter would allow it.
        faultinject.check("fuse.site")
    assert not os.path.exists(faults_file + ".fuse-test.fired")


def test_faultinject_server_drop_and_client_error(faults_file):
    """The wired-in sites: a server-side drop eats the reply (caller
    timeout governs), a client-side error raises typed pre-send."""
    from ray_tpu.core.rpc import RpcClient, RpcServer

    srv = RpcServer({"echo": lambda x: x}, name="ftinj")
    try:
        cli = RpcClient(srv.addr)
        assert cli.call("echo", 1) == 1
        with Faults(faults_file) as f:
            drop = f.add("rpc.server.ftinj.echo", "drop")
            with pytest.raises(TimeoutError):
                cli.call("echo", 2, timeout=0.5)
            f.remove(drop)
            f.add("rpc.client.echo", "error")
            with pytest.raises(FaultInjected):
                cli.call("echo", 3, timeout=5.0)
        assert cli.call("echo", 4, timeout=5.0) == 4  # rules cleared
        cli.close()
    finally:
        srv.stop()


# ------------------------------------- ReconnectingClient backoff


def test_reconnecting_backoff_exponential_capped(monkeypatch):
    from ray_tpu.core.rpc import ReconnectingClient

    monkeypatch.setattr("random.random", lambda: 0.5)  # jitter x1.0
    base = config.rpc_reconnect_backoff_base_ms / 1e3
    cap = config.rpc_reconnect_backoff_cap_ms / 1e3
    pauses = [ReconnectingClient._backoff_s(a) for a in range(12)]
    assert pauses[0] == pytest.approx(base)  # first retry stays FAST
    for a in range(1, 12):
        assert pauses[a] == pytest.approx(min(cap, base * 2 ** a))
    assert pauses[-1] == pytest.approx(cap)  # dead peer: capped trickle
    # jitter bounds: 0.5x..1.5x of the deterministic value
    monkeypatch.undo()
    for a in (0, 3, 11):
        want = min(cap, base * 2 ** a)
        got = ReconnectingClient._backoff_s(a)
        assert 0.5 * want <= got <= 1.5 * want


def test_reconnecting_client_retries_through_window(monkeypatch):
    """Dead peer: the call keeps (backed-off) retrying until the window
    closes, then surfaces the transport error."""
    import socket as _socket

    from ray_tpu.core.rpc import ReconnectingClient, RpcError

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = s.getsockname()
    s.close()
    monkeypatch.setattr(config, "rpc_connect_retries", 1)
    monkeypatch.setattr(config, "rpc_reconnect_backoff_base_ms", 5)
    monkeypatch.setattr(config, "rpc_reconnect_backoff_cap_ms", 40)
    cli = ReconnectingClient(dead, retry_window_s=0.6)
    t0 = time.monotonic()
    with pytest.raises((RpcError, OSError)):
        cli.call("ping", timeout=5.0)
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.55  # kept retrying through the window
    cli.close()


def test_reconnect_storm_still_detected_with_backoff(monkeypatch):
    """Satellite guard: the backoff must NOT starve the doctor's
    reconnect-storm signature — a client courting a dead controller
    still burns enough real dials inside one window (each re-dial is
    `rpc_connect_retries` failed connects, all counted)."""
    import socket as _socket

    from ray_tpu import doctor
    from ray_tpu.core.rpc import ReconnectingClient, RpcError

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = s.getsockname()
    s.close()
    monkeypatch.setattr(config, "rpc_connect_retries", 4)
    monkeypatch.setattr(config, "rpc_reconnect_backoff_base_ms", 2)
    monkeypatch.setattr(config, "rpc_reconnect_backoff_cap_ms", 50)
    before = _agg()
    cli = ReconnectingClient(dead, retry_window_s=0.8,
                             role="controller")
    with pytest.raises((RpcError, OSError)):
        cli.call("ping", timeout=5.0)
    cli.close()
    findings = doctor.diagnose(before, _agg(), 1.0)
    storm = [f for f in findings if f["signature"] == "reconnect-storm"]
    assert storm and storm[0]["severity"] == "critical"
    assert "never answers" in storm[0]["summary"]


# ------------------------------------------------ epoch lease fencing


def test_epoch_bump_and_fenced_kv_write():
    from ray_tpu.core.controller import Controller
    from ray_tpu.core.rpc import RpcClient
    from ray_tpu.core.rpc_stubs import ControllerStub

    c = Controller()
    try:
        stub = ControllerStub(RpcClient(c.address))
        e1 = stub.epoch_bump("ft_test")
        e2 = stub.epoch_bump("ft_test")
        assert e2 == e1 + 1
        assert stub.kv_put_fenced("ft:k", b"new", e2, "ft_test") is True
        # The zombie (deposed epoch) write is REJECTED, not applied.
        assert stub.kv_put_fenced("ft:k", b"old", e1, "ft_test") is False
        assert stub.kv_get("ft:k") == b"new"
    finally:
        c.stop()


def test_pubsub_hub_fences_stale_epoch_publish():
    from ray_tpu.core.pubsub import Pubsub

    hub = Pubsub()
    v1 = hub.publish("chan", "k", {"who": "new"}, epoch=2)
    assert v1 == 1
    assert hub.publish("chan", "k", {"who": "zombie"}, 99, 1) is None
    assert hub.snapshot("chan")["k"][1]["who"] == "new"
    # equal/newer epochs keep publishing; epoch-less keys stay unfenced
    assert hub.publish("chan", "k", {"who": "new2"}, epoch=2) == 2
    assert hub.publish("chan", "other", "x") == 1


def test_router_ignores_zombie_epoch_snapshot():
    from ray_tpu.core.ids import ActorID
    from ray_tpu.serve.deployment import _Router

    r = _Router.__new__(_Router)
    r.name = "fence-test"
    r._lock = threading.Lock()
    r._replicas = []
    r._inflight = {}
    r._version = 0
    r._ctrl_epoch = 0
    r._have_snapshot = threading.Event()
    r._max_ongoing = 8
    r._deleted = False
    rep = {"actor_id": ActorID.from_random().binary(),
           "replica_id": "a#0"}
    r._apply(5, {"epoch": 2, "replicas": [rep],
                 "max_ongoing_requests": 8})
    assert len(r._replicas) == 1 and r._ctrl_epoch == 2
    # zombie snapshot (older epoch, higher version): ignored, but the
    # version clock advances so the poll loop stays live
    r._apply(6, {"epoch": 1, "replicas": [], "deleted": True})
    assert len(r._replicas) == 1 and not r._deleted
    assert r._version == 6
    # the successor's snapshot applies
    r._apply(7, {"epoch": 3, "replicas": [rep, rep],
                 "max_ongoing_requests": 8})
    assert len(r._replicas) == 2 and r._ctrl_epoch == 3


# ------------------------------------ restart-with-adoption (logical)


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield ray_start_regular
    try:
        serve.shutdown()
    except Exception:
        pass


@pytest.fixture
def slice_faults_cluster(tmp_path, monkeypatch):
    """Cluster whose node advertises a virtual 2x4 slice, with fault
    injection plumbed into every process (env set before init)."""
    path = str(tmp_path / "faults.json")
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICE", "2x4")
    monkeypatch.setenv("RAY_TPU_FAULTINJECT_PATH", path)
    monkeypatch.setattr(config, "faultinject_path", path)
    faultinject.reset_counters()
    core = ray_tpu.init(num_cpus=4)
    yield core, path
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()
    faultinject.reset_counters()


def _quiesce(ctl: ServeController) -> None:
    """Simulated death of a DIRECT controller instance: loops stop,
    state stays exactly where the 'crash' left it."""
    ctl._stop.set()
    time.sleep(0.05)


def _epoch(core) -> int:
    blob = core.controller.call("kv_get", f"__epoch__:{EPOCH_NAME}")
    return int(blob) if blob else 0


@pytest.mark.timeout_s(240)
def test_restart_adopts_live_replicas_and_fences_zombie(serve_cluster):
    """The core adoption contract, on direct controller instances (no
    process kill — the SIGKILL path is the chaos test below): a
    successor restores the checkpoint, ADOPTS both replicas (same actor
    ids — no respawn), bumps the epoch, and the predecessor's next
    checkpoint write self-fences."""
    from ray_tpu.core import serialization

    class Echo:
        def __call__(self, req):
            return {"pid": os.getpid()}

        def pid(self, _=None):
            return os.getpid()

    c1 = ServeController()
    assert c1._epoch >= 1
    v = c1.deploy("adopt_app", serialization.dumps_function(Echo), (),
                  {}, {"num_replicas": 2})
    assert v is not None
    ids1 = sorted(r.handle.actor_id.hex()
                  for r in c1._deployments["adopt_app"].replicas)
    assert len(ids1) == 2
    _quiesce(c1)

    c2 = ServeController()
    try:
        assert c2._epoch == c1._epoch + 1
        ids2 = sorted(r.handle.actor_id.hex()
                      for r in c2._deployments["adopt_app"].replicas)
        # Adopted in place: SAME actor ids — no respawn, no cold start.
        assert ids2 == ids1
        # Requests route through the adopted set.
        handle = serve.get_deployment_handle("adopt_app")
        out = handle.remote({"x": 1}).result(timeout=60)
        assert "pid" in out
        # The router applied the successor's epoch-stamped snapshot.
        from ray_tpu.serve.deployment import _Router

        deadline = time.monotonic() + 10
        router = _Router.get("adopt_app")
        while router._ctrl_epoch < c2._epoch:
            assert time.monotonic() < deadline, router._ctrl_epoch
            time.sleep(0.05)
        # ZOMBIE: the predecessor wakes up and tries to checkpoint —
        # the fenced KV write is rejected and it ceases mutation.
        c1._fenced = False
        c1._stop.clear()
        c1._save_state()
        assert c1._fenced and c1._stop.is_set()
        # ... and its snapshot publishes are refused by the hub.
        assert c1._publish(c1._deployments["adopt_app"]) is None
    finally:
        _quiesce(c2)
        serve.delete("adopt_app")


@pytest.mark.timeout_s(240)
def test_pending_release_survives_restart(slice_faults_cluster):
    """Satellite regression: a controller that dies with a QUEUED
    sub-slice release (the release RPC failed) must free the chips
    after restart — the queue is checkpointed and the successor's
    reconcile loop resumes the retries."""
    core, faults_path = slice_faults_cluster
    from ray_tpu.core import serialization

    class MeshStub:
        def __init__(self, mesh_shape=None):
            self.mesh_shape = mesh_shape

        def __call__(self, req):
            return {"ok": True}

    def topo():
        return core.controller.call("topology_state")

    c1 = ServeController()
    c1.deploy("meshapp", serialization.dumps_function(MeshStub), (), {},
              {"num_replicas": 1, "mesh_shape": [1, 2]})
    (slice_state,) = topo()["slices"].values()
    assert len(slice_state["reservations"]) == 1
    assert slice_state["chips_free"] == 6

    with Faults(faults_path) as faults:
        faults.add("rpc.client.release_subslice", "error")
        # Delete kills the replica; the injected release failure queues
        # the reservation id — and the queue checkpoints immediately.
        c1.delete("meshapp")
        with c1._lock:
            assert c1._pending_releases, "release was not queued"
        # Controller dies with the release still queued (the rule keeps
        # every retry failing until then).
        _quiesce(c1)
    # Successor restores the queue and its retries now succeed.
    c2 = ServeController()
    try:
        deadline = time.monotonic() + 15
        while True:
            (slice_state,) = topo()["slices"].values()
            if (not slice_state["reservations"]
                    and slice_state["chips_free"] == 8):
                break
            assert time.monotonic() < deadline, slice_state
            time.sleep(0.1)
        assert "meshapp" not in c2.status()
    finally:
        _quiesce(c2)


# --------------------------------------------- chaos acceptance (E2E)


@pytest.mark.chaos
@pytest.mark.timeout_s(300)
def test_chaos_sigkill_controller_mid_decode(slice_faults_cluster):
    """ISSUE 12 acceptance: SIGKILL the serve controller actor (via the
    fault harness, at a named site) while decode streams are in flight
    and autoscaling is active —

    * zero in-flight stream failures (tokens keep flowing throughout);
    * the restarted controller ADOPTS live replicas without respawn
      (actor ids unchanged) and replaces only the dead one (a replica
      SIGKILLed during the outage — the overlapping-death case);
    * no double-reserved or leaked sub-slices (`topology_state` shows
      the SAME single reservation before and after);
    * routing snapshots resume within `serve_mttr_bound_s`;
    * a fenced zombie-epoch write is rejected.
    """
    core, faults_path = slice_faults_cluster
    from ray_tpu.core.rpc_stubs import ControllerStub
    from ray_tpu.serve.deployment import AutoscalingConfig, _Router

    class Streamer:
        """CPU 'decode' loop: slow enough that streams straddle the
        controller outage; shape mirrors a token stream."""

        def __call__(self, req):
            for i in range(int(req["n"])):
                time.sleep(0.04)
                yield i

        def pid(self, _=None):
            return os.getpid()

    class MeshStub:
        def __init__(self, mesh_shape=None):
            self.mesh_shape = mesh_shape

        def __call__(self, req):
            return {"ok": True}

    serve.run(
        serve.deployment(
            Streamer, num_replicas=2,
            autoscaling_config=AutoscalingConfig(
                min_replicas=2, max_replicas=3,
                target_ongoing_requests=16.0, upscale_delay_s=30.0,
                downscale_delay_s=600.0)).options(
            max_concurrency=16, max_ongoing_requests=32),
        name="llm_ft")
    serve.run(serve.deployment(MeshStub, num_replicas=1,
                               mesh_shape=(1, 2)), name="mesh_ft")
    handle = serve.get_deployment_handle("llm_ft")

    # Pre-kill ground truth: replica pids, actor ids, topology.
    pids = set()
    deadline = time.monotonic() + 60
    while len(pids) < 2 and time.monotonic() < deadline:
        pids.add(handle.options(method_name="pid").remote(None)
                 .result(timeout=60))
    assert len(pids) == 2
    st0 = serve.status(timeout=30)
    names0 = set(st0["llm_ft"]["replica_ids"])
    router = _Router.get("llm_ft")
    with router._lock:
        actor_ids0 = {r["id"]: r["handle"].actor_id.hex()
                      for r in router._replicas}
    (slice0,) = core.controller.call("topology_state")["slices"].values()
    assert len(slice0["reservations"]) == 1
    (resv0,) = slice0["reservations"].keys()
    e0 = _epoch(core)

    # In-flight streams that straddle the whole outage (~4 s each).
    results, errors = [], []

    def client(i):
        try:
            results.append(list(handle.stream({"n": 100})))
        except Exception as e:  # noqa: BLE001
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.4)  # streams admitted and mid-"decode"

    with Faults(faults_path) as faults:
        kill = faults.add("serve.controller.reconcile_tick", "die",
                          once_global=True, rule_id="kill-ctl")
        deadline = time.monotonic() + 30
        while not faults.marker_fired(kill):
            assert time.monotonic() < deadline, "controller kill never fired"
            time.sleep(0.05)
        faults.clear()

    # Zero in-flight stream failures: the streams run to completion
    # while NO controller exists (nothing here pokes the dead actor,
    # so the restart has not even begun) — controller death is a
    # non-event for the data plane.
    for t in threads:
        t.join()
    assert not errors, errors
    assert len(results) == 6
    assert all(r == list(range(100)) for r in results)

    # Overlapping death: one replica dies while the controller is
    # STILL down. The restarted controller must adopt the survivor and
    # replace only this one.
    victim_pid = next(iter(pids))
    os.kill(victim_pid, signal.SIGKILL)

    # First status probe reports the dead controller -> restart ->
    # restore -> adoption; poll until the control plane reconverges.
    # MTTR clock starts at DETECTION (this probe): in production the
    # proxies' route refresh detects within ~2 s; here the test idled
    # the cluster deliberately while the streams drained.
    t_detect = time.monotonic()
    deadline = t_detect + float(config.serve_mttr_bound_s) + 60
    while True:
        st = serve.status(timeout=5)
        rec = st.get("llm_ft") or {}
        if (not rec.get("degraded") and _epoch(core) > e0
                and len(rec.get("replica_ids", ())) == 2):
            break
        assert time.monotonic() < deadline, st
        time.sleep(0.25)

    # Routing snapshots resumed (epoch-stamped) within the MTTR bound.
    deadline = t_detect + float(config.serve_mttr_bound_s)
    while router._ctrl_epoch <= e0:
        assert time.monotonic() < deadline, \
            f"snapshots not flowing within {config.serve_mttr_bound_s}s"
        time.sleep(0.05)
    mttr = time.monotonic() - t_detect
    assert mttr <= config.serve_mttr_bound_s

    # Adoption: the surviving replica kept its ACTOR (id unchanged —
    # no respawn); only the SIGKILLed one was replaced.
    st = serve.status(timeout=30)
    names_now = set(st["llm_ft"]["replica_ids"])
    survivors = names0 & names_now
    assert survivors, (names0, names_now)
    with router._lock:
        actor_ids_now = {r["id"]: r["handle"].actor_id.hex()
                         for r in router._replicas}
    adopted = [n for n in survivors
               if actor_ids_now.get(n) == actor_ids0.get(n)]
    assert adopted, (actor_ids0, actor_ids_now)
    # The mesh replica was adopted with its reservation: same single
    # reservation id, same free-chip count — nothing double-reserved,
    # nothing leaked.
    (slice1,) = core.controller.call("topology_state")["slices"].values()
    assert list(slice1["reservations"].keys()) == [resv0]
    assert slice1["chips_free"] == slice0["chips_free"]
    assert set(st["mesh_ft"]["replica_ids"]) \
        == set(st0["mesh_ft"]["replica_ids"])

    # Fenced zombie-epoch write: the pre-kill epoch can no longer
    # touch the checkpoint.
    assert ControllerStub(core.controller).kv_put_fenced(
        STATE_KEY, b"zombie", e0, EPOCH_NAME) is False


@pytest.mark.chaos
@pytest.mark.timeout_s(240)
@pytest.mark.slow  # 11s: outage soak; chaos sigkill test keeps the
# controller-FT path in tier-1 (PR 16 rebudget)
def test_serve_during_outage_http_and_soft_status(slice_faults_cluster):
    """Satellite: routers and proxies keep serving from their cached
    snapshot while the controller is DOWN (restart stretched to a
    multi-second window via an injected init delay): streaming requests
    complete through the real HTTP proxy, and `serve.status()` degrades
    soft (cached view, `degraded: True`) instead of raising."""
    import json as _json
    import urllib.request

    core, faults_path = slice_faults_cluster

    class Streamer:
        def __call__(self, req):
            for i in range(int(req["n"])):
                time.sleep(0.03)
                yield i

    serve.run(serve.deployment(Streamer, num_replicas=2).options(
        max_concurrency=8, max_ongoing_requests=16), name="out_app")
    host, port = serve.start_http()

    def post_stream(n, timeout=60):
        req = urllib.request.Request(
            f"http://{host}:{port}/out_app",
            data=_json.dumps({"n": n}).encode(),
            headers={"X-Serve-Stream": "1"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            items = [_json.loads(line) for line in resp if line.strip()]
        return items

    assert post_stream(3) == [0, 1, 2]  # warm (routes cached too)
    e0 = _epoch(core)

    with Faults(faults_path) as faults:
        # The restarted controller's __init__ stalls 8 s: the outage
        # becomes an observable window instead of a ~1 s blip.
        faults.add("serve.controller.init", "delay", delay_s=8.0,
                   times=1, rule_id="slow-restart")
        kill = faults.add("serve.controller.reconcile_tick", "die",
                          once_global=True, rule_id="kill-ctl2")
        deadline = time.monotonic() + 30
        while not faults.marker_fired(kill):
            assert time.monotonic() < deadline
            time.sleep(0.05)
        # DURING the outage: the data plane serves. The status probe
        # both degrades soft AND doubles as the failure report that
        # starts the (delayed) restart.
        st = serve.status(timeout=2)
        assert st.get("out_app", {}).get("degraded") is True, st
        assert st["out_app"]["replicas"] == 2
        assert post_stream(10) == list(range(10))  # through the proxy
        # Handle creation during the outage works off cached snapshots.
        h = serve.get_deployment_handle("out_app")
        assert list(h.stream({"n": 4})) == [0, 1, 2, 3]
        # Still down after the data-plane traffic: proves the streams
        # above really ran inside the outage window, not after it.
        st = serve.status(timeout=2)
        assert st.get("out_app", {}).get("degraded") is True, st
        faults.clear()

    # Recovery: controller back, same replicas, status un-degrades.
    deadline = time.monotonic() + 60
    while True:
        st = serve.status(timeout=5)
        rec = st.get("out_app") or {}
        if not rec.get("degraded") and len(rec.get("replica_ids",
                                                   ())) == 2:
            break
        assert time.monotonic() < deadline, st
        time.sleep(0.25)
    assert _epoch(core) > e0
    assert post_stream(3) == [0, 1, 2]


# ------------------------------------------------- doctor signatures


def test_doctor_detects_controller_flapping():
    from ray_tpu import doctor
    from ray_tpu.serve import metrics as sm

    sm.CONTROLLER_EPOCH.set(3.0)
    before = _agg()
    sm.CONTROLLER_EPOCH.set(6.0)  # three bumps inside one window
    findings = doctor.diagnose(before, _agg(), 2.0)
    flap = [f for f in findings
            if f["signature"] == "controller-flapping"]
    assert flap and flap[0]["severity"] == "critical"
    assert "crash-looping" in flap[0]["summary"]
    # one bump (a normal restart) stays quiet
    sm.CONTROLLER_EPOCH.set(7.0)
    after = _agg()
    sm.CONTROLLER_EPOCH.set(7.0)
    quiet = doctor.diagnose(after, _agg(), 2.0)
    assert not [f for f in quiet
                if f["signature"] == "controller-flapping"]


def test_doctor_detects_orphan_replica():
    from ray_tpu import doctor
    from ray_tpu.serve import metrics as sm

    sm.CONTROLLER_EPOCH.set(7.0)
    sm.REPLICA_EPOCH.set(2.0, {"deployment": "dft"})
    snap = _agg()
    # Persistent across the window (same stale epoch in both
    # snapshots) -> orphan; the summary names the deployment.
    findings = doctor.diagnose(snap, snap, 2.0)
    orphan = [f for f in findings if f["signature"] == "orphan-replica"]
    assert orphan and "'dft'" in orphan[0]["summary"]
    assert "no controller reconciles" in orphan[0]["summary"]
    # Adoption heals it: replica re-pushed to the live epoch -> quiet.
    sm.REPLICA_EPOCH.set(7.0, {"deployment": "dft"})
    healed = _agg()
    assert not [f for f in doctor.diagnose(healed, healed, 2.0)
                if f["signature"] == "orphan-replica"]


def test_doctor_adoption_transient_is_not_orphan():
    """A replica that lags ONE window behind (the adopt push raced the
    snapshot) must not page anyone: the condition has to hold in BOTH
    snapshots."""
    from ray_tpu import doctor
    from ray_tpu.serve import metrics as sm

    sm.CONTROLLER_EPOCH.set(9.0)
    sm.REPLICA_EPOCH.set(9.0, {"deployment": "dft"})
    before = _agg()  # healthy
    sm.CONTROLLER_EPOCH.set(10.0)  # restart happened mid-window
    sm.REPLICA_EPOCH.set(9.0, {"deployment": "dft"})  # not yet adopted
    after = _agg()
    assert not [f for f in doctor.diagnose(before, after, 2.0)
                if f["signature"] == "orphan-replica"]
    # leave the registry consistent for the healthy-cluster gates
    sm.REPLICA_EPOCH.set(10.0, {"deployment": "dft"})


def test_doctor_new_signatures_quiet_on_healthy_and_in_catalog():
    from ray_tpu import doctor
    from ray_tpu.serve import metrics as sm

    sm.CONTROLLER_EPOCH.set(11.0)
    sm.REPLICA_EPOCH.set(11.0, {"deployment": "dft"})
    snap = _agg()
    findings = doctor.diagnose(snap, snap, 2.0)
    assert not [f for f in findings
                if f["signature"] in ("controller-flapping",
                                      "orphan-replica")]
    text = doctor.render([])
    assert "controller-flapping" in text and "orphan-replica" in text
