"""GSPMD model-parallel decode: bit-exactness vs the single-chip path,
engine integration, and mesh-native serving end-to-end.

The correctness contract (ROADMAP #1): sharding NEVER changes logits.
The decode rules partition only output/batch dims and all-gather before
every contracted operand (``wo``/``w_down`` replicated), so every output
element is produced by the single-chip reduction order — asserted here
with ``np.array_equal``, not a tolerance, across mesh shapes 1x8 / 2x4 /
8x1 on the virtual CPU mesh for prefill, suffix-prefill and paged
decode.
"""

import json
import time
from functools import partial

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve

MESHES = [
    # PR 20 rebudget (7.3s/7.1s): the 8x1 run stays THE tier-1
    # bit-exact gate; the other orientations re-trace the same
    # program under a rotated mesh
    pytest.param((1, 8), marks=pytest.mark.slow),
    pytest.param((2, 4), marks=pytest.mark.slow),
    (8, 1),
]


def _cfg():
    from ray_tpu.models import llama

    # every sharded dim divisible by 8 so all three mesh shapes exercise
    # real weight sharding (indivisible configs replicate — tested
    # separately)
    return llama.LlamaConfig(vocab_size=64, dim=32, n_layers=2,
                             n_heads=8, n_kv_heads=8, mlp_dim=64,
                             max_seq_len=128)


@pytest.fixture(scope="module")
def model():
    import jax

    from ray_tpu.models import llama

    cfg = _cfg()
    return cfg, llama.init_params(cfg, jax.random.key(0))


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(0)
    return rng.randint(1, 60, size=(8, 12)).astype(np.int32)


# ---------------------------------------------------- model-level exact


@pytest.fixture(scope="module")
def references(model, prompts):
    """Single-chip logits for prefill, suffix-prefill, decode steps and
    paged decode — the byte-level ground truth."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama_decode as ld

    cfg, params = model
    B, S = prompts.shape
    out = {}
    pf = jax.jit(partial(ld.prefill, config=cfg))
    lg, cache = pf(params, jnp.asarray(prompts),
                   ld.init_cache(cfg, B, 64))
    out["prefill"] = np.asarray(lg)
    dstep = jax.jit(partial(ld.decode_step, config=cfg))
    tok = jnp.argmax(lg, -1).astype(jnp.int32)
    for _ in range(4):
        lg, cache = dstep(params, cache, tok)
        tok = jnp.argmax(lg, -1).astype(jnp.int32)
    out["decode"] = np.asarray(lg)

    half = 6
    _, warm = pf(params, jnp.asarray(prompts[:, :half]),
                 ld.init_cache(cfg, B, 64))
    sfx = jax.jit(partial(ld.prefill_suffix, config=cfg))
    slg, _ = sfx(params, jnp.asarray(prompts[:, half:]), warm,
                 prefix_lens=jnp.full((B,), half, jnp.int32),
                 lengths=jnp.full((B,), S, jnp.int32))
    out["suffix"] = np.asarray(slg)

    T, pages, W = 8, 80, 8
    bt = np.arange(1, 1 + B * W, dtype=np.int32).reshape(B, W)
    ppf = jax.jit(partial(ld.paged_prefill, config=cfg))
    plg, pool = ppf(params, jnp.asarray(prompts),
                    ld.init_page_pool(cfg, pages, T), jnp.asarray(bt))
    pd = jax.jit(partial(ld.paged_decode_step, config=cfg))
    lens = jnp.full((B,), S, jnp.int32)
    tok = jnp.argmax(plg, -1).astype(jnp.int32)
    for _ in range(4):
        plg, pool, lens = pd(params, pool, jnp.asarray(bt), lens, tok)
        tok = jnp.argmax(plg, -1).astype(jnp.int32)
    out["paged"] = np.asarray(plg)
    out["bt"] = bt
    return out


@pytest.mark.parametrize("shape", MESHES, ids=lambda s: f"{s[0]}x{s[1]}")
def test_sharded_logits_bit_exact(model, prompts, references, shape):
    """Prefill, suffix-prefill and paged decode logits on every mesh
    shape are BYTE-identical to the single-chip programs."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama_decode as ld
    from ray_tpu.parallel.mesh import decode_mesh
    from ray_tpu.parallel.sharding import axis_rules

    cfg, params = model
    B, S = prompts.shape
    half = 6
    mesh = decode_mesh(shape)
    sparams, sh = ld.shard_decode_state(params, cfg, mesh)
    with axis_rules(mesh, sh["rules"]):
        pf = jax.jit(partial(ld.prefill, config=cfg),
                     out_shardings=(sh["replicated"], sh["cache"]))
        lg, cache = pf(sparams, jnp.asarray(prompts),
                       jax.device_put(ld.init_cache(cfg, B, 64),
                                      sh["cache"]))
        assert np.array_equal(np.asarray(lg), references["prefill"])

        dstep = jax.jit(partial(ld.decode_step, config=cfg),
                        out_shardings=(sh["replicated"], sh["cache"]))
        tok = jnp.argmax(lg, -1).astype(jnp.int32)
        for _ in range(4):
            lg, cache = dstep(sparams, cache, tok)
            tok = jnp.argmax(lg, -1).astype(jnp.int32)
        assert np.array_equal(np.asarray(lg), references["decode"])

        _, warm = pf(sparams, jnp.asarray(prompts[:, :half]),
                     jax.device_put(ld.init_cache(cfg, B, 64),
                                    sh["cache"]))
        sfx = jax.jit(partial(ld.prefill_suffix, config=cfg),
                      out_shardings=(sh["replicated"], sh["cache"]))
        slg, _ = sfx(sparams, jnp.asarray(prompts[:, half:]), warm,
                     prefix_lens=jnp.full((B,), half, jnp.int32),
                     lengths=jnp.full((B,), S, jnp.int32))
        assert np.array_equal(np.asarray(slg), references["suffix"])

        bt = references["bt"]
        pool_sh = {"k": sh["pool"]["k"], "v": sh["pool"]["v"]}
        ppf = jax.jit(partial(ld.paged_prefill, config=cfg),
                      out_shardings=(sh["replicated"], pool_sh))
        plg, pool = ppf(sparams, jnp.asarray(prompts),
                        jax.device_put(ld.init_page_pool(cfg, 80, 8),
                                       pool_sh), jnp.asarray(bt))
        pd = jax.jit(partial(ld.paged_decode_step, config=cfg),
                     out_shardings=(sh["replicated"], pool_sh,
                                    sh["replicated"]))
        lens = jnp.full((B,), S, jnp.int32)
        tok = jnp.argmax(plg, -1).astype(jnp.int32)
        for _ in range(4):
            plg, pool, lens = pd(sparams, pool, jnp.asarray(bt), lens,
                                 tok)
            tok = jnp.argmax(plg, -1).astype(jnp.int32)
        assert np.array_equal(np.asarray(plg), references["paged"])


def test_indivisible_dims_replicate_not_pad(model):
    """A GQA config whose kv heads don't divide the model axis keeps
    bit-exactness by replicating the head dims (mlp still shards)."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.parallel.mesh import decode_mesh
    from ray_tpu.parallel.sharding import decode_rules

    cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, mlp_dim=64, max_seq_len=128)
    rules = decode_rules(cfg, decode_mesh((2, 4)))
    assert rules["kv_heads"] is None and rules["heads"] is None
    assert rules["vocab"] is None      # 61 % 4 != 0
    assert rules["mlp"] == "model"     # 64 % 4 == 0
    # and (8, 1): model axis 1 -> everything effectively unsharded
    rules1 = decode_rules(cfg, decode_mesh((8, 1)))
    assert rules1["heads"] == "model"  # axis size 1: moot but legal


def test_decode_param_axes_replicates_contraction_operands():
    from ray_tpu.models import llama

    axes = llama.decode_param_axes(_cfg())
    assert axes["layers"]["wo"] == ("layers", None, None, None)
    assert axes["layers"]["w_down"] == ("layers", None, None)
    # output-dim projections still shard
    assert axes["layers"]["wq"][2] == "heads"
    assert axes["lm_head"][1] == "vocab"


# --------------------------------------------------------- engine level


def _drive(eng, prompts, n_tok=6):
    reqs = [eng.submit(list(p), max_new_tokens=n_tok)
            for p in prompts]
    for _ in range(120):
        if all(r.done.is_set() for r in reqs):
            break
        eng.step()
    assert all(r.done.is_set() for r in reqs)
    return [r.output for r in reqs]


@pytest.mark.slow  # PR 20 rebudget (10.5s): engine-level mesh parity;
# the 8x1 sharded-logits bit-exact gate stays tier-1
def test_engine_mesh_matches_single_chip(model):
    """The full continuous-batching engine (admission waves, prefix
    suffix splice, paged pool, chunked prefill) emits identical token
    streams with and without a mesh."""
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = model
    prompts = [[5, 9, 2], [7], [11, 3, 4, 8, 1], [9, 1]]
    ref = _drive(DecodeEngine(params, cfg, slots=4, capacity=64),
                 prompts)
    out = _drive(DecodeEngine(params, cfg, slots=4, capacity=64,
                              mesh_shape=(2, 4)), prompts)
    assert out == ref

    paged_kw = dict(page_tokens=8, pool_pages=40, prefix_pool_entries=8,
                    prefill_chunk_tokens=16)
    ref_p = _drive(DecodeEngine(params, cfg, slots=4, capacity=64,
                                **paged_kw), prompts)
    out_p = _drive(DecodeEngine(params, cfg, slots=4, capacity=64,
                                mesh_shape=(2, 4), **paged_kw), prompts)
    assert out_p == ref_p


def test_engine_validates_slot_divisibility(model):
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = model
    with pytest.raises(ValueError, match="multiple of the mesh"):
        DecodeEngine(params, cfg, slots=3, capacity=64,
                     mesh_shape=(2, 4))


def test_engine_stats_report_mesh(model):
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = model
    eng = DecodeEngine(params, cfg, slots=4, capacity=64,
                       mesh_shape=(4, 2))
    s = eng.stats()
    assert s["chips"] == 8 and s["mesh_shape"] == [4, 2]
    single = DecodeEngine(params, cfg, slots=2, capacity=64)
    assert single.stats()["chips"] == 1
    assert single.stats()["mesh_shape"] is None


# ------------------------------------------------------- router slice


def test_router_prefers_ici_local_replica(monkeypatch):
    """With two unsaturated replicas on different slices, the router
    picks the one on the caller's own slice (controller snapshots carry
    slice ids; locality never overrides saturation)."""
    import importlib

    # ray_tpu.serve re-exports the @deployment decorator under the same
    # name as the module; import the module itself.
    dep_mod = importlib.import_module("ray_tpu.serve.deployment")

    router = dep_mod._Router.__new__(dep_mod._Router)
    import threading

    router.name = "t"
    router._lock = threading.Lock()
    router._inflight = {}
    router._version = 1
    router._max_ongoing = 2
    router._deleted = False
    router._replicas = [
        {"handle": object(), "id": "a", "models": set(),
         "prefixes": set(), "slice_id": "far"},
        {"handle": object(), "id": "b", "models": set(),
         "prefixes": set(), "slice_id": "here"},
    ]
    monkeypatch.setattr(dep_mod, "_local_slice_cache", ["here"])
    for _ in range(8):
        chosen = router._pick("")
        assert chosen["id"] == "b"
        router._release(chosen)
    # saturated local replica: load escapes locality
    router._inflight["b"] = 2
    assert router._pick("")["id"] == "a"


# -------------------------------------------------- serve plane e2e


@pytest.fixture
def mesh_serve_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_VIRTUAL_SLICE", "2x4")
    core = ray_tpu.init(num_cpus=4)
    yield core
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


@pytest.mark.timeout_s(420)
@pytest.mark.slow  # 9s: serve-plane mesh replica; engine-level mesh
# parity tests stay in tier-1 (PR 16 rebudget)
def test_mesh_replica_serves_end_to_end(mesh_serve_cluster, model):
    """Acceptance: a deployment with mesh_shape=(2, 4) spawns ONE
    replica spanning all 8 virtual devices, streams through proxy ->
    router -> replica, its outputs are bit-exact vs the single-chip
    engine at equal capacity, status reports the topology, and a second
    8-chip deployment is refused placement until the slice frees."""
    import urllib.request

    from ray_tpu.serve.decode import DecodeEngine, LlamaDecodeDeployment

    cfg, params = model
    ref = _drive(DecodeEngine(params, cfg, slots=4, capacity=64),
                 [[5, 9, 2]], n_tok=5)[0]

    serve.run(
        serve.deployment(LlamaDecodeDeployment).options(
            max_concurrency=4).bind(config=cfg, slots=4, capacity=64,
                                    seed=0, mesh_shape=(2, 4)),
        name="llm", ready_timeout_s=180)
    handle = serve.get_deployment_handle("llm")
    out = handle.remote({"tokens": [5, 9, 2],
                         "max_new_tokens": 5}).result(timeout=180)
    assert out["tokens"] == ref

    toks = list(handle.stream({"tokens": [5, 9, 2], "max_new_tokens": 5,
                               "stream": True}))
    assert toks == ref

    host, port = serve.start_http()
    req = urllib.request.Request(
        f"http://{host}:{port}/llm",
        data=json.dumps({"tokens": [5, 9, 2], "max_new_tokens": 5,
                         "stream": True}).encode(),
        headers={"X-Serve-Stream": "1"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        lines = [json.loads(ln) for ln in resp.read().splitlines() if ln]
    assert lines == ref

    # one replica spans the whole slice, and status says where it lives
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = serve.status()["llm"]
        if st["replica_topology"] and \
                st["replica_topology"][0]["mesh_shape"]:
            break
        time.sleep(0.5)
    assert st["replicas"] == 1
    assert st["chips_in_use"] == 8
    topo = st["replica_topology"][0]
    assert topo["mesh_shape"] == [2, 4] and topo["chips"] == 8
    assert topo["slice_id"].startswith("virtual-")
    assert topo["sub_slice"] == {"origin": [0, 0], "shape": [2, 4]}

    # the slice is fully reserved: a second 8-chip replica is refused —
    # the deployment stays at 0 replicas (queued), it is never placed
    # on a fragment
    serve.run(
        serve.deployment(LlamaDecodeDeployment, name="llm2").options(
            max_concurrency=2).bind(config=cfg, slots=4, capacity=64,
                                    mesh_shape=(2, 4)),
        name="llm2", ready_timeout_s=15)
    time.sleep(1.5)
    st2 = serve.status()["llm2"]
    assert st2["replicas"] == 0 and st2["chips_in_use"] == 0
    slice_state = list(
        ray_tpu.cluster_topology()["slices"].values())[0]
    assert slice_state["chips_free"] == 0
    assert len(slice_state["reservations"]) == 1

    # freeing the slice lets the queued deployment place (reconcile)
    serve.delete("llm")
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        st2 = serve.status().get("llm2", {})
        if st2.get("replicas"):
            break
        time.sleep(0.5)
    assert st2.get("replicas") == 1
    assert st2.get("chips_in_use") == 8
