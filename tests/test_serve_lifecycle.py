"""Request-lifecycle fault tolerance in the serve plane (ISSUE 3).

Covers the four lifecycle mechanisms end to end:

* deadlines — checked at admission and at every ``step()``; the slot is
  freed with a typed ``DeadlineExceededError`` instead of decoding for a
  caller that already gave up;
* cooperative cancellation — client disconnect / generator close flows
  into ``DecodeEngine.cancel``: queued requests never touch the device,
  active ones free their slot within one step, prefix-pool pins drop;
* bounded admission — past ``decode_queue_max`` the engine sheds at
  enqueue (<1 ms) with ``OverloadedError`` -> HTTP 503 + Retry-After;
* retry budgets — the handle retries replica death with exponential
  backoff + jitter, never mid-stream and never past the deadline
  (chaos: SIGKILL a replica mid-decode, requests re-route and the
  controller replaces it).
"""

import json
import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.errors import (DeadlineExceededError, OverloadedError,
                                 RequestCancelledError)


def _tiny():
    import jax

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=61, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, mlp_dim=64, max_seq_len=128)
    params = llama.init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture
def serve_cluster(ray_start_regular):
    yield ray_start_regular
    try:
        serve.shutdown()
    except Exception:
        pass


# ------------------------------------------------------------- deadlines


def test_deadline_expired_at_submit_rejected():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=1, capacity=64,
                       prefix_pool_entries=0)
    with pytest.raises(DeadlineExceededError):
        eng.submit([1, 2], max_new_tokens=2, deadline_s=0.0)
    assert eng.stats()["deadline_exceeded"] == 1
    eng.shutdown()


def test_deadline_at_admission_never_touches_device():
    """A queued request whose deadline passes before a slot frees is
    retired at admission — no prefill is spent on it."""
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=1, capacity=64,
                       prefix_pool_entries=0)
    hog = eng.submit([1, 2, 3], max_new_tokens=40)
    eng.step()  # hog takes the only slot
    late = eng.submit([4, 5], max_new_tokens=5, deadline_s=0.05)
    time.sleep(0.6)  # expire while queued (past the purge throttle too)
    tokens_before = eng.tokens_out
    eng.step()
    assert late.done.is_set()
    assert late.status == "deadline_exceeded"
    assert late.slot == -1 and late.generated == 0
    with pytest.raises(DeadlineExceededError):
        late.raise_for_status()
    # The step decoded ONLY the hog's token: no device work for `late`.
    assert eng.tokens_out == tokens_before + 1
    assert eng.stats()["deadline_exceeded"] == 1
    assert not hog.done.is_set()
    eng.shutdown()


def test_deadline_mid_decode_frees_slot_healthy_unaffected():
    """An active request whose deadline passes mid-generation is finished
    with deadline_exceeded at the next step boundary; a healthy request
    decoding alongside completes bit-exactly."""
    from ray_tpu.models import llama_decode
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=64,
                       prefix_pool_entries=0)
    doomed = eng.submit([6, 7], max_new_tokens=50, deadline_s=0.15)
    healthy = eng.submit([1, 2], max_new_tokens=20)
    while not doomed.done.is_set():
        eng.step()
        time.sleep(0.02)  # slow "device" so the deadline lands mid-decode
    assert doomed.status == "deadline_exceeded"
    assert 0 < doomed.generated < 50
    while not healthy.done.is_set():
        eng.step()
    assert healthy.status == "completed"
    solo = llama_decode.generate(
        params, __import__("numpy").array([[1, 2]], dtype="int32"), cfg,
        max_new_tokens=20)
    assert healthy.output == list(__import__("numpy").asarray(solo)[0])
    s = eng.stats()
    assert s["free_slots"] == 2 and s["deadline_exceeded"] == 1
    eng.shutdown()


# ----------------------------------------------------------- cancellation


def test_cancel_queued_request_never_touches_device():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=1, capacity=64,
                       prefix_pool_entries=0)
    hog = eng.submit([1, 2, 3], max_new_tokens=30)
    eng.step()
    queued = eng.submit([4, 5], max_new_tokens=5)
    assert eng.cancel(queued.request_id)
    # Load drops IMMEDIATELY (autoscaler must not scale for dead queue
    # entries), before the loop even runs.
    assert eng.stats()["load"] == 1
    tokens_before = eng.tokens_out
    eng.step()
    assert queued.done.is_set() and queued.status == "cancelled"
    assert queued.slot == -1 and queued.generated == 0
    assert eng.tokens_out == tokens_before + 1  # only the hog stepped
    with pytest.raises(RequestCancelledError):
        queued.raise_for_status()
    assert not eng.cancel(queued.request_id)  # idempotent on finished
    eng.shutdown()


def test_cancel_active_frees_slot_within_one_step_and_prefix_pins():
    """Cancelling an active request frees its slot at the next step and
    leaves every prefix-pool row unpinned (refcounts back to zero)."""
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=64,
                       prefix_pool_entries=4, prefix_match_min_tokens=4)
    # Seed the prefix pool with a long prompt, then hit it.
    seed = eng.submit(list(range(1, 25)), max_new_tokens=2)
    while not seed.done.is_set():
        eng.step()
    victim = eng.submit(list(range(1, 25)) + [30, 31], max_new_tokens=30)
    eng.step()  # admitted via the prefix-hit path
    assert victim.slot >= 0 and victim.prefix_len > 0
    assert eng.cancel(victim.request_id)
    eng.step()  # ONE step boundary frees the slot
    assert victim.done.is_set() and victim.status == "cancelled"
    assert eng.stats()["free_slots"] == 2
    assert eng.stats()["cancelled"] == 1
    # Every pool row's splice pin has been released.
    refcounts = [e.refcount for e in eng.prefix._entries.values()]
    assert refcounts and all(rc == 0 for rc in refcounts), refcounts
    eng.shutdown()


def test_stream_generator_close_cancels_engine_request():
    """Closing the deployment's streaming generator (what every client
    disconnect reduces to) cancels the engine request: the slot frees
    within one step of the running decode loop."""
    from ray_tpu.serve.decode import LlamaDecodeDeployment

    cfg, _ = _tiny()
    dep = LlamaDecodeDeployment(config=cfg, slots=2, capacity=64,
                                prefix_pool_entries=0)
    # Slow the decode loop (~20 ms/token) so the stream cannot complete
    # before the close lands — the test is about cancellation, not speed.
    orig_decode = dep.engine._decode

    def slow(*a, **k):
        time.sleep(0.02)
        return orig_decode(*a, **k)

    dep.engine._decode = slow
    try:
        gen = dep.stream({"tokens": [5, 9, 2], "max_new_tokens": 60})
        first = next(gen)
        assert isinstance(first, int)
        gen.close()  # client went away
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = dep.engine.stats()
            if s["active"] == 0 and s["cancelled"] == 1:
                break
            time.sleep(0.02)
        s = dep.engine.stats()
        assert s["active"] == 0 and s["free_slots"] == 2, s
        assert s["cancelled"] == 1, s
    finally:
        dep.engine.shutdown()


# ---------------------------------------------------------- load shedding


def test_queue_cap_sheds_fast_with_retry_after():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=1, capacity=64, queue_max=2,
                       prefix_pool_entries=0)
    hog = eng.submit([1, 2, 3], max_new_tokens=40)
    eng.step()
    eng.submit([4], max_new_tokens=4)
    eng.submit([5], max_new_tokens=4)
    t0 = time.perf_counter()
    with pytest.raises(OverloadedError) as ei:
        eng.submit([6], max_new_tokens=4)
    shed_latency = time.perf_counter() - t0
    # Acceptance bar is p99 < 50 ms; a single sample gets the same bound
    # (typical is ~microseconds — the check is qsize + raise, no device).
    assert shed_latency < 0.05, f"shed took {shed_latency * 1e3:.1f} ms"
    assert ei.value.retry_after_s > 0
    s = eng.stats()
    assert s["shed"] == 1
    assert s["queued"] <= s["queue_max"] == 2
    eng.shutdown()


def test_queue_default_cap_is_slots_x8():
    from ray_tpu.serve.decode import DecodeEngine

    cfg, params = _tiny()
    eng = DecodeEngine(params, cfg, slots=2, capacity=64,
                       prefix_pool_entries=0)
    assert eng.queue_max == 16
    eng.shutdown()


# ------------------------------------------------- through the serve stack


@pytest.mark.slow  # 18.5s: full proxy+handle sweep; PR 16 rebudget
@pytest.mark.timeout_s(240)
def test_deadline_and_overload_through_handle_and_proxy(serve_cluster):
    """Deadline + shedding end to end: handle timeout_s propagates into
    the engine (typed DeadlineExceededError back out), the queue cap
    maps to HTTP 503 + Retry-After, and a header deadline maps to 504."""
    import urllib.error
    import urllib.request

    from ray_tpu.serve.decode import LlamaDecodeDeployment
    from ray_tpu.serve.proxy import _lifecycle_error

    cfg, _ = _tiny()

    class SlowDecode(LlamaDecodeDeployment):
        """The tiny model decodes at ~0.3 ms/step — too fast for wall-
        clock deadline/overload scenarios. Slow each decode step to
        20 ms so generations hold slots for seconds."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            orig = self.engine._decode

            def slow(*args, **kwargs):
                time.sleep(0.02)
                return orig(*args, **kwargs)

            self.engine._decode = slow

    serve.run(
        serve.deployment(SlowDecode).options(
            max_concurrency=8, max_ongoing_requests=64).bind(
            config=cfg, slots=1, capacity=128, queue_max=1),
        name="llm_fault")
    handle = serve.get_deployment_handle("llm_fault")

    # Warm one request through (replica up, programs compiled).
    out = handle.remote({"tokens": [5, 9, 2],
                         "max_new_tokens": 2}).result(timeout=120)
    assert len(out["tokens"]) == 2

    # Deadline through the handle: a ~2.4 s generation against a 0.5 s
    # timeout_s comes back as a typed DeadlineExceededError, promptly.
    fut = handle.options(timeout_s=0.5).remote(
        {"tokens": [5, 9, 2], "max_new_tokens": 120})
    t0 = time.monotonic()
    with pytest.raises(Exception) as ei:
        fut.result(timeout=60)
    assert isinstance(_lifecycle_error(ei.value), DeadlineExceededError), \
        repr(ei.value)
    assert time.monotonic() - t0 < 30

    # Overload through the proxy: saturate the single slot + queue_max=1,
    # then a burst must see at least one 503 with Retry-After.
    host, port = serve.start_http()

    def post(payload, headers=None, timeout=60):
        req = urllib.request.Request(
            f"http://{host}:{port}/llm_fault",
            data=json.dumps(payload).encode(), headers=headers or {})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()

    # Stagger the hogs: hog1 must be ADMITTED (slot busy) before hog2 is
    # submitted, or hog2 itself gets shed by the queue_max=1 cap and the
    # burst below finds an empty queue.
    hogs = [threading.Thread(
        target=lambda: post({"tokens": [5, 9, 2], "max_new_tokens": 120},
                            timeout=120)) for _ in range(2)]
    hogs[0].start()
    time.sleep(0.6)  # hog1 admitted (decode loop idle-wait is 50 ms)
    hogs[1].start()
    time.sleep(0.6)  # hog2 parked in the pending queue (cap reached)
    saw_503 = None
    for _ in range(10):
        try:
            post({"tokens": [1, 2], "max_new_tokens": 2}, timeout=30)
        except urllib.error.HTTPError as e:
            if e.code == 503:
                saw_503 = e
                break
        time.sleep(0.1)
    assert saw_503 is not None, "no 503 under overload"
    assert int(saw_503.headers["Retry-After"]) >= 1
    for t in hogs:
        t.join()

    # Header deadline through the proxy: queue a long generation behind
    # a fresh hog with a 0.4 s budget -> 504 (the engine's typed
    # DeadlineExceeded mapped by the proxy).
    hog = threading.Thread(
        target=lambda: post({"tokens": [5, 9, 2], "max_new_tokens": 120},
                            timeout=120))
    hog.start()
    time.sleep(0.5)  # hog holds the slot for ~2.4 s
    with pytest.raises(urllib.error.HTTPError) as he:
        post({"tokens": [5, 9, 2], "max_new_tokens": 120},
             headers={"X-Request-Timeout-S": "0.4"}, timeout=60)
    assert he.value.code == 504
    hog.join()


# ------------------------------------------------------------------ chaos


@pytest.mark.chaos
@pytest.mark.slow  # 24 s: replica kill + reroute + heal
@pytest.mark.timeout_s(300)
def test_kill_replica_mid_decode_requests_reroute_and_heal(serve_cluster):
    """SIGKILL one of two decode replicas while non-streaming requests
    are in flight: (a) queued/in-flight requests re-route to the
    survivor within the handle retry budget and complete transparently,
    (b) the survivor ends with no wedged slots and zero prefix-pool
    pins, (c) the controller replaces the dead replica."""
    from ray_tpu.serve.decode import LlamaDecodeDeployment

    cfg, _ = _tiny()

    class KillableDecode(LlamaDecodeDeployment):
        STEP_DELAY_S = 0.03  # ~1 s per 30-token generation

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            orig = self.engine._decode

            def slow(*args, **kwargs):
                time.sleep(self.STEP_DELAY_S)
                return orig(*args, **kwargs)

            self.engine._decode = slow

        def __call__(self, request):
            out = super().__call__(request)
            if isinstance(out, dict):
                out["pid"] = os.getpid()
            return out

        def pid(self, _=None):
            return os.getpid()

        def probe(self, _=None):
            s = self.engine.stats()
            refs = ([e.refcount for e in
                     self.engine.prefix._entries.values()]
                    if self.engine.prefix is not None else [])
            return {"free_slots": s["free_slots"], "active": s["active"],
                    "pid": os.getpid(), "refcounts": refs}

    serve.run(
        serve.deployment(KillableDecode, num_replicas=2).options(
            max_concurrency=8, max_ongoing_requests=32).bind(
            config=cfg, slots=2, capacity=128,
            prefix_pool_entries=4, prefix_match_min_tokens=4),
        name="llm_chaos")
    handle = serve.get_deployment_handle("llm_chaos")

    # Find both replica pids (routing is load-balanced; poke until 2).
    pids = set()
    deadline = time.monotonic() + 120
    while len(pids) < 2 and time.monotonic() < deadline:
        pids.add(handle.options(method_name="pid").remote(None)
                 .result(timeout=60))
    assert len(pids) == 2, f"never saw both replicas: {pids}"

    # Seed the shared prefix: the victim is the replica that served it —
    # prefix-affinity steers the client wave there, so the SIGKILL lands
    # on a replica with decode work in flight.
    prompt = list(range(1, 21))
    warm = handle.remote({"tokens": prompt + [39],
                          "max_new_tokens": 2}).result(timeout=120)
    victim = warm["pid"]

    results = {}
    errors = []

    def client(i):
        try:
            out = handle.remote(
                {"tokens": prompt + [40 + i],
                 "max_new_tokens": 30}).result(timeout=180)
            results[i] = out["tokens"]
        except Exception as e:  # noqa: BLE001
            errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.5)  # requests admitted and mid-decode
    os.kill(victim, signal.SIGKILL)
    for t in threads:
        t.join()

    # (a)+(b of ISSUE) every non-streaming request completed via retry.
    assert not errors, f"requests failed despite retry budget: {errors}"
    assert len(results) == 8
    assert all(len(v) == 30 for v in results.values())

    # (b) survivor: no wedged slots, prefix pins back to zero.
    deadline = time.monotonic() + 60
    probe = None
    while time.monotonic() < deadline:
        probe = handle.options(method_name="probe").remote(None).result(
            timeout=60)
        if probe["active"] == 0 and probe["free_slots"] == 2:
            break
        time.sleep(0.5)
    assert probe is not None and probe["active"] == 0, probe
    assert probe["free_slots"] == 2, probe
    assert all(rc == 0 for rc in probe["refcounts"]), probe

    # (c) the controller replaces the dead replica.
    deadline = time.monotonic() + 120
    while serve.status()["llm_chaos"]["replicas"] < 2:
        assert time.monotonic() < deadline, "replica never replaced"
        time.sleep(0.5)
