"""CI slice of the scale envelope (VERDICT r2 #2; full harness: envelope.py,
measured rows: ENVELOPE.md; reference: release/benchmarks/README.md:5-32).

Reduced sizes, same mechanisms: many live raylets in one machine, a
cluster-wide task storm with scheduling-latency percentiles, a PG storm, an
actor wave, and a control-plane registry at hundreds of nodes under a
heartbeat storm. Assertions are completion + generous latency bounds (this
suite runs on loaded CI boxes — see tests/conftest.py watchdog), so a pass
means "no deadlock, no melt", not a perf number; perf lives in ENVELOPE.md.
"""

import threading
import time

import pytest


def _pctl(xs, p):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


@pytest.mark.timeout_s(170)
def test_control_plane_500_nodes_heartbeat_storm():
    """500 registered nodes, 8-thread heartbeat storm, pick_node stays
    responsive and always feasible."""
    from ray_tpu.core.controller import Controller
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.rpc import RpcClient

    ctrl = Controller()
    try:
        ids = [NodeID.from_random() for _ in range(500)]
        cli = RpcClient(ctrl.address)
        for nid in ids:
            cli.call("register_node", nid.binary(), ("127.0.0.1", 1),
                     {"CPU": 16.0}, {})
        assert sum(n["alive"] for n in ctrl.list_nodes()) == 500

        stop = threading.Event()
        beats = [0] * 8

        def hb(i):
            c = RpcClient(ctrl.address)
            while not stop.is_set():
                for nid in ids[i::8]:
                    if stop.is_set():
                        break
                    c.call("heartbeat", nid.binary(), {"CPU": 12.0}, 1)
                    beats[i] += 1

        threads = [threading.Thread(target=hb, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        lat = []
        pc = RpcClient(ctrl.address)
        for _ in range(200):
            s = time.perf_counter()
            assert pc.call("pick_node", {"CPU": 1.0}, None, None, None)
            lat.append((time.perf_counter() - s) * 1000)
        stop.set()
        for t in threads:
            t.join(5)
        # 500 nodes @ 1 Hz needs 500 beats/s; the storm sustained far more.
        assert sum(beats) > 500, beats
        # Generous load-tolerant bound; measured p99 ~13ms on an idle box.
        assert _pctl(lat, 0.99) < 2000, f"pick_node p99 {_pctl(lat, 0.99)}ms"
    finally:
        ctrl.stop()


@pytest.mark.timeout_s(170)
@pytest.mark.slow  # 8s: 50-raylet storm soak; PR 16 rebudget
def test_50_raylets_task_pg_storms(ray_start_cluster):
    """50 live raylets: 600-task storm completes with sane scheduling
    latency; 120 simultaneous placement groups all reserve and release."""
    import ray_tpu
    from ray_tpu.core.placement import placement_group, remove_placement_group

    cluster = ray_start_cluster
    for _ in range(50):
        cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(60)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def noop(x):
        return x

    # Warm a few worker pools (fork-bound); the storm then measures
    # scheduling, not process creation.
    ray_tpu.get([noop.remote(i) for i in range(32)], timeout=120)

    t_storm = time.time()
    out = ray_tpu.get([noop.remote(i) for i in range(600)], timeout=120)
    assert out == list(range(600))

    # Scheduling latency percentiles from the controller's task events.
    time.sleep(2.0)
    from ray_tpu.core.runtime import get_core_worker

    events = get_core_worker().controller.call("list_task_events", 3000)
    sched = [(e["lease_ts"] - e["submitted_ts"]) * 1000 for e in events
             if e.get("lease_ts") and e.get("state") == "FINISHED"
             and e.get("submitted_ts", 0) >= t_storm]
    assert len(sched) >= 500, f"only {len(sched)} events recorded"
    assert _pctl(sched, 0.5) < 5000, f"sched p50 {_pctl(sched, 0.5)}ms"

    # PG storm: 120 one-bundle groups, all ready, then removed.
    pgs = [placement_group([{"CPU": 0.01}], strategy="PACK")
           for _ in range(120)]
    assert all(pg.ready(timeout=60) for pg in pgs)
    for pg in pgs:
        remove_placement_group(pg)
    # Released resources are usable again: one more task wave completes.
    assert ray_tpu.get([noop.remote(i) for i in range(50)],
                       timeout=120) == list(range(50))


@pytest.mark.timeout_s(170)
def test_actor_wave_across_nodes(ray_start_cluster):
    """A wave of dedicated-worker actors lands across many nodes; all
    respond, then all die clean."""
    import ray_tpu

    cluster = ray_start_cluster
    for _ in range(12):
        cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(30)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    class Member:
        def whoami(self):
            import os

            return os.getpid()

    actors = [Member.options(num_cpus=0.01).remote() for _ in range(16)]
    pids = ray_tpu.get([a.whoami.remote() for a in actors], timeout=160)
    assert len(set(pids)) == 16
    for a in actors:
        ray_tpu.kill(a)


@pytest.mark.slow  # 6s: 100-actor surge soak; envelope stays via the
# cross-node actor wave (the raylet storm is already marked);
# PR 18 rebudget
@pytest.mark.timeout_s(170)
def test_actor_surge_forkserver(ray_start_regular):
    """A burst of 100 actors — the Serve-replica-surge shape — must come up
    at forkserver speed, not interpreter-spawn speed (reference: prestarted
    worker pool, worker_pool.h:357; 40k-actor envelope row,
    release/benchmarks/README.md:12). The bound is ~6x looser than the
    measured rate (>50/s on an idle box) to tolerate CI load, but still
    several times faster than the old fork wall (~4.7/s => 21s)."""
    import ray_tpu

    @ray_tpu.remote
    class Replica:
        def ping(self):
            import os

            return os.getpid()

    # Warm the template (first fork starts the forkserver process).
    warm = Replica.options(num_cpus=0.001).remote()
    ray_tpu.get(warm.ping.remote(), timeout=60)
    ray_tpu.kill(warm)

    t0 = time.time()
    actors = [Replica.options(num_cpus=0.001).remote() for _ in range(100)]
    pids = ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
    wall = time.time() - t0
    assert len(set(pids)) == 100
    assert wall < 12.0, f"100-actor surge took {wall:.1f}s (fork wall?)"
    for a in actors:
        ray_tpu.kill(a)
