"""graftlint v2 tests: guarded-by inference, resource lifetime, RPC
contract, and the callgraph fidelity upgrades they ride on.

Same layering as tests/test_analysis.py:

1. Per-rule TP/TN fixtures — synthetic modules fed straight to the
   checkers (no jax, no cluster, no sockets).
2. Callgraph fidelity fixtures: bound-method aliasing, decorated
   functions, functools.partial targets, self-attribute typing.
3. CLI plumbing: --jobs, --diff, --stats-json.
4. Per-family repo-stays-clean gates (the broad gate lives in
   test_analysis.py; these pin each NEW family individually so a
   regression names the family that rotted).
"""

import json
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu.analysis import repo_root, run_analysis
from ray_tpu.analysis import rules
from ray_tpu.analysis import guarded_by, lifetime, rpc_contract
from ray_tpu.analysis.callgraph import CallGraph
from ray_tpu.analysis.core import Project, SourceFile


def project_of(**modules) -> Project:
    files = []
    for name, src in modules.items():
        rel = f"ray_tpu/{name}.py"
        files.append(SourceFile(f"/fixture/{rel}", rel,
                                textwrap.dedent(src)))
    return Project("/fixture", files)


def run_checker(check, project):
    graph = CallGraph(project)
    findings = check(graph)
    by_rel = {f.relpath: f for f in project.files}
    return [f for f in findings
            if not by_rel[f.path].suppressed(f.rule, f.line)]


# ---------------------------------------------------- guarded-by inference

GUARDED_TP = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._stop = False

        def start(self):
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                with self._lock:
                    self._n += 1

        def snapshot(self):
            with self._lock:
                return self._n

        def racy_reset(self):
            self._n = 0
"""


def test_guarded_by_flags_unguarded_write():
    found = run_checker(guarded_by.check, project_of(mod=GUARDED_TP))
    assert [f.rule for f in found] == [rules.UNGUARDED_FIELD]
    f = found[0]
    assert f.symbol == "Counter.racy_reset"
    assert "_n" in f.message and "_lock" in f.message
    # the message names where the concurrency comes from
    assert "thread:" in f.message or "caller" in f.message


def test_guarded_by_majority_and_init_exemption():
    # 2 locked sites vs 1 unlocked -> guarded; __init__ writes exempt.
    found = run_checker(guarded_by.check, project_of(mod=GUARDED_TP))
    assert all(f.symbol != "Counter.__init__" for f in found)


GUARDED_TIE = """
    import threading

    class Tie:
        def __init__(self):
            self._lock = threading.Lock()
            self._x = 0

        def start(self):
            threading.Thread(target=self._loop).start()

        def _loop(self):
            with self._lock:
                self._x += 1

        def unlocked_bump(self):
            self._x += 1
"""


def test_guarded_by_exact_tie_infers_nothing():
    # 1 locked site vs 1 unlocked: no strict majority -> no findings
    # (and the locked-site minimum of 2 is not met either).
    found = run_checker(guarded_by.check, project_of(mod=GUARDED_TIE))
    assert found == []


GUARDED_SINGLE_THREAD = """
    import threading

    class NoThreads:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def locked_a(self):
            with self._lock:
                self._n += 1

        def locked_b(self):
            with self._lock:
                self._n -= 1

        def unlocked(self):
            self._n = 0
"""


def test_guarded_by_requires_thread_reachability():
    # Same inconsistent locking, but no thread entry points anywhere:
    # nothing is concurrent, nothing is flagged.
    found = run_checker(guarded_by.check,
                        project_of(mod=GUARDED_SINGLE_THREAD))
    assert found == []


def test_guarded_by_immutable_field_skipped():
    src = """
        import threading

        class ReadMostly:
            def __init__(self):
                self._lock = threading.Lock()
                self._cfg = {"a": 1}

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    use(self._cfg)
                with self._lock:
                    use2(self._cfg)

            def read_unlocked(self):
                return self._cfg
    """
    # _cfg is never written outside __init__ -> effectively immutable
    found = run_checker(guarded_by.check, project_of(mod=src))
    assert found == []


def test_guarded_by_locked_suffix_convention_exempt():
    src = """
        import threading

        class Conv:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = 0

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    self._q += 1
                with self._lock:
                    self._q += 2
                with self._lock:
                    self._flush_locked()

            def _flush_locked(self):
                self._q = 0
    """
    found = run_checker(guarded_by.check, project_of(mod=src))
    assert found == []


def test_guarded_by_rpc_handlers_are_pool_concurrent():
    src = """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self._hits = 0
                self._srv = RpcServer(handlers={"bump": self.bump,
                                                "peek": self.peek})

            def bump(self):
                with self._lock:
                    self._hits += 1
                with self._lock:
                    self._hits += 1

            def peek(self):
                return self._hits

        class RpcServer:
            def __init__(self, handlers):
                self.handlers = handlers
    """
    found = run_checker(guarded_by.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["Server.peek"]
    assert "rpc:" in found[0].message


# -------------------------------------------------- resource lifetime

def test_lifetime_socket_leak_on_exception_path():
    src = """
        import socket

        def leaky(addr):
            sock = socket.socket()
            handshake(sock, addr)
            sock.close()

        def protected(addr):
            sock = socket.socket()
            try:
                handshake(sock, addr)
            finally:
                sock.close()

        def with_ok(addr):
            with socket.socket() as sock:
                handshake(sock, addr)
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["leaky"]
    assert found[0].rule == rules.RESOURCE_LEAK
    assert "escaping exception" in found[0].message


def test_lifetime_early_return_leak():
    src = """
        import socket

        def early_return(addr):
            sock = socket.socket()
            if bad(addr):
                return None
            sock.close()
            return True
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert len(found) == 1 and found[0].symbol == "early_return"


def test_lifetime_ownership_transfers():
    src = """
        import socket

        def returned(addr):
            sock = socket.socket()
            return sock

        def stored(self, addr):
            sock = socket.socket()
            self.sock = sock

        def wrapped(addr):
            sock = socket.socket()
            conn = Conn(sock)
            register(conn)

        class Conn:
            def __init__(self, sock):
                self.sock = sock
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    # return / attribute store / constructor wrap all transfer ownership
    assert found == [], [f.render() for f in found]


def test_lifetime_setup_call_between_acquire_and_return_leaks():
    """The _connect bug class: post-connect setup raising between the
    acquire and the ownership-transferring return orphans the fd."""
    src = """
        import socket

        def dial(addr):
            sock = socket.socket()
            sock.connect(addr)
            return sock
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["dial"]
    assert "escaping exception" in found[0].message


def test_lifetime_close_in_typed_handler_ok():
    src = """
        import socket

        def dial(addr):
            sock = socket.socket()
            try:
                sock.connect(addr)
                return sock
            except OSError:
                sock.close()
                raise
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert found == [], [f.render() for f in found]


def test_lifetime_handler_without_release_still_leaks():
    src = """
        import socket

        def swallow_and_leak(addr):
            sock = socket.socket()
            try:
                sock.connect(addr)
            except OSError:
                log("boom")
            return None
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["swallow_and_leak"]


def test_lifetime_selector_register_pair_and_drop_helper():
    src = """
        class Reactor:
            def risky(self, sock, st):
                self._selector.register(sock, 1, st)
                arm(st)
                self._selector.unregister(sock)

            def via_drop(self, sock, st):
                self._selector.register(sock, 1, st)
                arm(st)
                self._drop(st)

            def _drop(self, st):
                self._selector.unregister(st.sock)
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    # both paths leak only if arm() raises: register/unregister pairing
    # with the release OUTSIDE a finally -> exception-path finding; the
    # _drop release resolves through the call graph, so via_drop pairs
    # exactly like the direct unregister
    assert sorted(f.symbol for f in found) == ["Reactor.risky",
                                               "Reactor.via_drop"]
    assert all("escaping exception" in f.message for f in found)


def test_lifetime_register_without_any_release_is_ownership():
    src = """
        class Server:
            def __init__(self, sock):
                self._selector.register(sock, 1, None)
                self.more_setup()
    """
    # never unregisters anywhere: the registration IS the object state
    found = run_checker(lifetime.check, project_of(mod=src))
    assert found == []


def test_lifetime_loop_scoped_registration_not_leaked_across_iters():
    src = """
        class Acceptor:
            def accept_loop(self):
                while True:
                    sock = self.sock_accept()
                    self._selector.register(sock, 1, None)
                    self.might_raise()
                    self._maybe_drop(sock)

            def _maybe_drop(self, sock):
                self._selector.unregister(sock)
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    # might_raise() mid-iteration with the registration live IS a leak
    assert [f.symbol for f in found] == ["Acceptor.accept_loop"]

    src_ok = """
        class Acceptor:
            def accept_loop(self):
                while True:
                    sock = self.sock_accept()
                    try:
                        self._selector.register(sock, 1, None)
                    except OSError:
                        self._drop(sock)
                    # iteration completes: the registration is settled
                    # object state, not a leak in flight

            def _drop(self, sock):
                self._selector.unregister(sock)
    """
    found = run_checker(lifetime.check, project_of(mod=src_ok))
    assert found == [], [f.render() for f in found]


def test_lifetime_slot_pool_and_refcount_pairs():
    src = """
        class Engine:
            def leaky_slot(self):
                slot = self._free.pop()
                self.prefill(slot)
                self._free.append(slot)

            def safe_slot(self):
                slot = self._free.pop()
                try:
                    self.prefill(slot)
                finally:
                    self._free.append(slot)

        class Cache:
            def leaky_pin(self, ent):
                ent.refcount += 1
                self.splice(ent)
                ent.refcount -= 1
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert sorted(f.symbol for f in found) == ["Cache.leaky_pin",
                                               "Engine.leaky_slot"]


def test_lifetime_page_allocator_leaks():
    """The paged-KV allocator idiom (serve/paging.py): pages leased with
    ``self._pages.alloc(n)`` must be freed or ownership-transferred on
    every path — a block leak on a cancel/deadline/retire path pins HBM
    forever."""
    src = """
        class Engine:
            def leaky_admit(self, req):
                pages = self._pages.alloc(4)
                self.prefill(req, pages)      # raises -> pages stranded
                self._pages.free(pages)

            def early_return_leak(self, req):
                pages = self._pages.alloc(4)
                if req.cancelled:
                    return None               # retire path drops pages
                self._pages.free(pages)
                return True
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert sorted(f.symbol for f in found) == ["Engine.early_return_leak",
                                               "Engine.leaky_admit"]
    assert all(f.rule == rules.RESOURCE_LEAK for f in found)


def test_lifetime_page_allocator_clean_idioms():
    """Release-in-finally, ownership transfer into engine state, and
    freeing a collection CONTAINING the lease (``free(shared + fresh)``)
    all discharge the page lease."""
    src = """
        class Engine:
            def finally_frees(self, req):
                pages = self._pages.alloc(4)
                try:
                    self.prefill(req, pages)
                finally:
                    self._pages.free(pages)

            def transfers(self, slot):
                pages = self._pages.alloc(4)
                self._slot_pages[slot] = pages

            def frees_collection(self, shared):
                pages = self._pages.alloc(4)
                self._pages.free(shared + pages)
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert found == [], [f.render() for f in found]


def test_lifetime_page_incref_pair():
    """allocator.incref/decref is a method pair: an escaping exception
    between pin and unpin leaks the reference."""
    src = """
        class Index:
            def leaky_pin(self, alloc, page):
                alloc.incref(page)
                self.splice(page)
                alloc.decref(page)
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["Index.leaky_pin"]


def test_lifetime_finally_loop_release_recognized():
    src = """
        def fork(a_path, b_path):
            a = b = None
            try:
                a = open(a_path, "ab")
                b = open(b_path, "ab")
                spawn(a, b)
            finally:
                for f in (a, b):
                    if f is not None:
                        f.close()
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert found == [], [f.render() for f in found]


def test_lifetime_generators_skipped():
    src = """
        import socket

        def gen(addr):
            sock = socket.socket()
            yield sock.recv(1)
            sock.close()
    """
    found = run_checker(lifetime.check, project_of(mod=src))
    assert found == []


# ----------------------------------------------------- RPC contract

RPC_BASE = """
    class Server:
        def __init__(self):
            self._srv = RpcServer(handlers={
                "echo": self.echo,
                "sum2": self.sum2,
                "varargs": self.varargs,
                "never_called": self.echo,
            }, inline_methods={"echo", "ghost"})
            self._srv.register("late", self.late)

        def echo(self, x):
            return x

        def sum2(self, a, b, scale=1):
            return (a + b) * scale

        def varargs(self, *args, **kwargs):
            return args

        def late(self):
            return None

    class RpcServer:
        def __init__(self, handlers, inline_methods=()):
            self.handlers = handlers

        def register(self, name, fn):
            self.handlers[name] = fn

    def caller(client):
        client.call("echo", 1)
        client.call("sum2", 1, 2, timeout=5.0)
        client.call("sum2", 1, 2, scale=3)
        client.call("varargs", 1, 2, 3, 4, anything="x")
        client.notify("late")
"""


def test_rpc_contract_clean_base():
    found = run_checker(rpc_contract.check, project_of(mod=RPC_BASE))
    by_rule = {}
    for f in found:
        by_rule.setdefault(f.rule, []).append(f)
    # "never_called" is dead; "ghost" inline entry names no handler
    assert [f.message.split('"')[1] for f in
            by_rule.get(rules.RPC_DEAD, [])] == ["never_called"]
    assert len(by_rule.get(rules.RPC_UNKNOWN, [])) == 1
    assert "ghost" in by_rule[rules.RPC_UNKNOWN][0].message
    assert rules.RPC_ARITY not in by_rule


def test_rpc_contract_unknown_and_arity():
    src = RPC_BASE + """
    def bad_callers(client):
        client.call("no_such_method")
        client.call("echo", 1, 2)
        client.call("sum2", 1)
        client.call("sum2", 1, 2, wrong_kw=4)
    """
    found = run_checker(rpc_contract.check, project_of(mod=src))
    msgs = {f.line: f for f in found}
    unknown = [f for f in found if f.rule == rules.RPC_UNKNOWN
               and "no_such_method" in f.message]
    assert len(unknown) == 1
    arity = [f for f in found if f.rule == rules.RPC_ARITY]
    labels = sorted(f.message.split('"')[1] for f in arity)
    # echo rejects 2 args; sum2 rejects 1 arg and the unknown keyword
    assert labels == ["echo", "sum2", "sum2"]


def test_rpc_contract_dynamic_name_and_splat_unchecked():
    src = RPC_BASE + """
    def dynamic(client, method, args):
        client.call(method, 1, 2, 3)
        client.call("varargs", *args)
    """
    found = run_checker(rpc_contract.check, project_of(mod=src))
    assert not any(f.rule == rules.RPC_ARITY for f in found)


def test_rpc_contract_timeout_kwarg_is_client_side():
    found = run_checker(rpc_contract.check, project_of(mod=RPC_BASE))
    # call("sum2", 1, 2, timeout=5.0) must NOT be an arity finding:
    # timeout is consumed by the transport
    assert not any(f.rule == rules.RPC_ARITY and "timeout" in f.message
                   for f in found)


# ------------------------------------------------- callgraph fidelity

def test_callgraph_bound_method_alias_resolves():
    src = """
        import time

        class C:
            def _on_readable(self):
                f = self._drain
                f()

            def _drain(self):
                time.sleep(1.0)
    """
    from ray_tpu.analysis import reactor_safety

    found = run_checker(reactor_safety.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["C._drain"]
    assert "_on_readable" in found[0].message


def test_callgraph_partial_thread_target_resolves():
    src = """
        import functools
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                t = threading.Thread(
                    target=functools.partial(self._loop, 3))
                t.start()

            def _loop(self, k):
                with self._lock:
                    self._n += k
                with self._lock:
                    self._n -= k

            def racy(self):
                self._n = 0
    """
    found = run_checker(guarded_by.check, project_of(mod=src))
    # the thread entry is only discoverable through the partial
    assert [f.symbol for f in found] == ["C.racy"]


def test_callgraph_decorated_functions_still_resolve():
    src = """
        import time

        def deco(fn):
            return fn

        class C:
            def _on_readable(self):
                self._helper()

            @deco
            def _helper(self):
                time.sleep(1.0)
    """
    from ray_tpu.analysis import reactor_safety

    found = run_checker(reactor_safety.check, project_of(mod=src))
    assert [f.symbol for f in found] == ["C._helper"]


def test_callgraph_self_attr_type_resolution():
    project = project_of(
        pub="""
            class Hub:
                def poll(self, key, cursor):
                    return cursor
        """,
        srv="""
            from ray_tpu.pub import Hub

            class S:
                def __init__(self):
                    self.hub = Hub()

                def go(self):
                    return self.hub.poll("k", 0)
        """)
    graph = CallGraph(project)
    info = graph.functions["ray_tpu.srv:S.go"]
    import ast as _ast

    call = next(n for n in _ast.walk(info.node)
                if isinstance(n, _ast.Call))
    callee, via_self = graph.resolve_call(call, info)
    assert callee == "ray_tpu.pub:Hub.poll"
    assert via_self is False  # different object: not self-chain evidence


# ------------------------------------------------------- CLI plumbing

@pytest.mark.slow  # 18s: two full repo runs; serial CLI runs stay in
# tier-1 (PR 16 rebudget)
def test_cli_jobs_parallel_matches_serial():
    serial, _ = run_analysis(jobs=1)
    parallel, _ = run_analysis(jobs=4)
    assert [f.to_json() for f in serial] == [f.to_json() for f in parallel]


@pytest.mark.slow  # 7s: full-repo diff run; diff-mode coverage stays
# via v3's diff_mode_covers_new_families + diff_one_file_stays_fast;
# PR 18 rebudget
def test_cli_diff_mode(tmp_path, capsys):
    from ray_tpu.analysis.__main__ import main

    # vs HEAD with a committed tree the diff may be empty or not; both
    # exits are clean because the repo is clean under strict
    rc = main(["--strict", "--diff", "HEAD"])
    assert rc == 0
    capsys.readouterr()
    # a ref that cannot be resolved is a usage error
    rc = main(["--strict", "--diff", "definitely-not-a-ref"])
    assert rc == 2


@pytest.mark.slow  # 7s: full-repo stats run; PR 16 rebudget
def test_cli_stats_json_artifact(tmp_path, capsys):
    from ray_tpu.analysis.__main__ import main

    out = tmp_path / "stats.json"
    assert main(["--stats-json", str(out)]) == 0
    capsys.readouterr()
    data = json.loads(out.read_text())
    assert set(data["rules"]) == set(rules.ALL_RULES)
    for rule, row in data["rules"].items():
        assert set(row) == {"raw", "pragma_suppressed",
                            "reported_unbaselined", "baselined"}
    # v2 rules ran over the repo
    assert data["files"] > 100
    assert data["rules"][rules.RESOURCE_LEAK]["raw"] >= 0


# --------------------------------------- per-family repo-clean gates

def _clean_under(select, paths=None):
    findings, _ = run_analysis(select=select, paths=paths)
    from ray_tpu.analysis import Baseline, DEFAULT_BASELINE

    baseline = Baseline.load(DEFAULT_BASELINE)
    new, _baselined, _stale = baseline.split(findings)
    return new


def test_repo_clean_guarded_by():
    new = _clean_under([rules.UNGUARDED_FIELD])
    assert new == [], "\n".join(f.render() for f in new)


def test_repo_clean_lifetime():
    new = _clean_under([rules.RESOURCE_LEAK])
    assert new == [], "\n".join(f.render() for f in new)


def test_repo_clean_rpc_contract():
    new = _clean_under([rules.RPC_UNKNOWN, rules.RPC_ARITY,
                        rules.RPC_DEAD])
    assert new == [], "\n".join(f.render() for f in new)


def test_rpc_registrations_actually_collected():
    """Guards the collector itself: if registration parsing silently
    broke, the dead-endpoint rule would go quiet instead of loud."""
    project = Project.load(repo_root())
    graph = CallGraph(project)
    regs, inline, handler_fqns = rpc_contract.collect_registrations(graph)
    names = {r.name for r in regs}
    # the four known servers' marquee endpoints
    assert {"heartbeat", "get_object", "lease_worker",
            "client_connect"} <= names
    assert len(regs) >= 60
    assert "heartbeat" in {n for n, *_ in inline}
    assert handler_fqns["heartbeat"].endswith("Controller.heartbeat")


def test_guarded_by_thread_entries_found_in_repo():
    project = Project.load(repo_root())
    graph = CallGraph(project)
    entries, self_concurrent = guarded_by.thread_entries(graph)
    # reactor + caller + a healthy population of real thread/pool/rpc
    # entries (55+ Thread()/submit() sites package-wide)
    assert "reactor" in entries and "caller" in entries
    assert sum(1 for k in entries if k.startswith("thread:")) >= 10
    assert any(k.startswith("rpc:") for k in entries)
    assert any(k in self_concurrent for k in entries)
