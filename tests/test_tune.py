"""Tune tests (model: reference ``tune/tests/test_tune.py`` +
``test_trial_scheduler_pbt.py``)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, PopulationBasedTraining, TuneConfig, Tuner


def test_grid_and_random_variants():
    from ray_tpu.tune.search import generate_variants

    space = {"lr": tune.grid_search([0.1, 0.01]),
             "wd": tune.uniform(0, 1), "fixed": 7}
    variants = generate_variants(space, num_samples=3, seed=0)
    assert len(variants) == 6
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert all(v["fixed"] == 7 for v in variants)


def test_tuner_basic(ray_start_regular):
    def trainable(config):
        from ray_tpu import tune as t

        for step in range(3):
            t.report({"score": config["x"] * (step + 1)})

    tuner = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="score", mode="max"),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 9


def test_tuner_trial_error_isolated(ray_start_regular):
    def trainable(config):
        from ray_tpu import tune as t

        if config["x"] == 2:
            raise RuntimeError("bad trial")
        t.report({"score": config["x"]})

    tuner = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="score", mode="max"),
    )
    grid = tuner.fit()
    errored = [r for r in grid if r.error]
    assert len(errored) == 1 and "bad trial" in errored[0].error
    assert grid.get_best_result().config["x"] == 3


def test_asha_stops_bad_trials(ray_start_regular):
    def trainable(config):
        from ray_tpu import tune as t

        for step in range(20):
            t.report({"loss": config["quality"] + step * 0.001})

    scheduler = ASHAScheduler(metric="loss", mode="min", max_t=20,
                              grace_period=2, reduction_factor=2)
    tuner = Tuner(
        trainable,
        param_space={"quality": tune.grid_search([0.1, 0.2, 5.0, 9.0])},
        tune_config=TuneConfig(metric="loss", mode="min",
                               scheduler=scheduler),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.config["quality"] == 0.1
    # At least one of the bad trials stopped early.
    iters = {r.config["quality"]: len(r.metrics_history) for r in grid}
    assert min(iters[5.0], iters[9.0]) < 20


def test_pbt_exploits_checkpoints(ray_start_regular, tmp_path):
    """Bottom trials adopt top trials' checkpointed state + perturbed
    hyperparams (the PBT clone/perturb loop, reference pbt.py)."""

    def trainable(config):
        import json
        import os
        import tempfile

        from ray_tpu import tune as t

        state = {"acc": 0.0}
        ckpt = t.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "s.json")) as f:
                state = json.load(f)
        for _ in range(12):
            import time

            time.sleep(0.05)  # keep reports slower than the driver poll loop
            state["acc"] += config["lr"]  # higher lr -> faster "learning"
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump(state, f)
            t.report({"acc": state["acc"]},
                     checkpoint=t.Checkpoint.from_directory(d))

    scheduler = PopulationBasedTraining(
        metric="acc", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.01, 0.1, 1.0]})
    tuner = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.01, 1.0])},
        tune_config=TuneConfig(metric="acc", mode="max", scheduler=scheduler),
        storage_path=str(tmp_path),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["acc"] >= 12 * 1.0 * 0.5  # top trial made progress
    # The originally-weak trial should have been exploited at least once:
    # its final acc must exceed what lr=0.01 alone could reach (12 * 0.01).
    weak = [r for r in grid if 0.005 < min(
        m.get("acc", 1e9) for m in r.metrics_history) < 0.2]
    if weak:  # exploitation happened mid-run
        assert max(m["acc"] for m in weak[0].metrics_history) > 0.5


def test_tuner_restore_resumes_errored(ray_start_regular, tmp_path):
    # Sweep 1: trials with flag>=2 crash after checkpointing step 0.
    # Restore with resume_errored: they resume FROM THEIR CHECKPOINT and
    # finish (reference: Tuner.restore, tune/tuner.py:171).
    from ray_tpu import tune
    from ray_tpu.train.session import get_checkpoint, report

    def flaky(config):
        import os

        ckpt = get_checkpoint()
        start = 0
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 3):
            import tempfile

            d = tempfile.mkdtemp()
            with open(os.path.join(d, "step.txt"), "w") as f:
                f.write(str(step))
            from ray_tpu.train.checkpoint import Checkpoint
            report({"loss": 10 - step, "step": step},
                   checkpoint=Checkpoint(d))
            if config["flag"] >= 2 and start == 0:
                raise RuntimeError("boom")

    storage = str(tmp_path)
    tuner = tune.Tuner(
        flaky,
        param_space={"flag": tune.grid_search([0, 1, 2, 3])},
        tune_config=tune.TuneConfig(metric="loss", mode="min"),
        storage_path=storage,
        name="restore_exp",
    )
    grid = tuner.fit()
    errored = [r for r in grid if r.error]
    assert len(errored) == 2, [r.error for r in grid]

    restored = tune.Tuner.restore(
        f"{storage}/restore_exp", flaky, resume_errored=True)
    grid2 = restored.fit()
    assert all(r.error is None for r in grid2), [r.error for r in grid2]
    # Resumed trials continued from their step-0 checkpoint (start=1), so
    # they never hit the start==0 crash and reach step 2.
    assert all(r.metrics["step"] == 2 for r in grid2)


# -------------------------------------------------------------- TPE search

def test_tpe_searcher_concentrates_on_optimum():
    """Pure searcher loop (no cluster): TPE's later suggestions cluster
    near the optimum of a quadratic (the defining model-based-search
    property; a head-to-head vs random would be a coin flip at this
    budget)."""
    from ray_tpu.tune import TPESearcher
    from ray_tpu.tune.search import uniform

    space = {"x": uniform(-1, 1), "y": uniform(-1, 1)}

    def objective(cfg):
        return (cfg["x"] - 0.3) ** 2 + (cfg["y"] + 0.2) ** 2

    tpe = TPESearcher(seed=0, n_startup_trials=8)
    tpe.set_search_properties("loss", "min", space)
    losses = []
    for i in range(48):
        cfg = tpe.suggest(f"t{i}")
        loss = objective(cfg)
        tpe.on_trial_complete(f"t{i}", {"loss": loss})
        losses.append(loss)
    assert min(losses) < 0.05, min(losses)
    # Informed phase is much tighter than the random startup phase.
    early = np.mean(losses[:8])
    late = np.mean(losses[-16:])
    assert late < early * 0.5, (early, late)


def test_tpe_categorical_concentrates():
    from ray_tpu.tune import TPESearcher
    from ray_tpu.tune.search import choice

    tpe = TPESearcher(seed=1, n_startup_trials=6)
    tpe.set_search_properties("loss", "min", {"arm": choice(["a", "b", "c"])})
    for i in range(30):
        cfg = tpe.suggest(f"t{i}")
        loss = {"a": 1.0, "b": 0.1, "c": 2.0}[cfg["arm"]]
        tpe.on_trial_complete(f"t{i}", {"loss": loss})
    picks = [tpe.suggest(f"p{i}")["arm"] for i in range(30)]
    assert picks.count("b") > 15, picks


@pytest.mark.timeout_s(240)
def test_tuner_with_tpe_search_alg(ray_start_regular):
    """TPE through the full Tuner: suggested configs flow to trials and
    completed results feed back (sequential model-based sweep)."""
    from ray_tpu import tune
    from ray_tpu.tune import TPESearcher, TuneConfig, Tuner
    from ray_tpu.tune.search import uniform

    def trainable(config):
        from ray_tpu import train

        train.report({"loss": (config["x"] - 0.5) ** 2})

    tuner = Tuner(
        trainable,
        param_space={"x": uniform(0, 1)},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=16,
                               max_concurrent_trials=2,
                               search_alg=TPESearcher(n_startup_trials=4,
                                                      seed=2)),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["loss"] < 0.05
    assert len(grid) == 16


# ---------------------------------------------------------------- Tune+Train
# (VERDICT r4 Missing #1: the reference's defining layering — a Trainer runs
# as a Tune trial, gang-scheduled with per-trial PG resources; reference:
# train/base_trainer.py:819,608 + tune/execution/placement_groups.py)


@pytest.mark.timeout_s(240)
def test_tuner_runs_jax_trainer_gang_trials(ray_start_regular, tmp_path):
    """Tuner(JaxTrainer): each trial is a gang-scheduled WorkerGroup (own
    placement group, 2 workers), the sampled config merges over
    train_loop_config, and metrics stream from rank 0."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_loop(config):
        from ray_tpu import train

        assert train.get_world_size() == 2
        for step in range(3):
            train.report({"score": config["lr"] * (step + 1),
                          "base": config["base"],
                          "rank": train.get_world_rank()})

    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"base": 7, "lr": 0.0},  # lr overridden per trial
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    tuner = Tuner(
        trainer,
        param_space={"lr": tune.grid_search([1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    assert not any(r.error for r in grid), [r.error for r in grid]
    best = grid.get_best_result()
    assert best.config["lr"] == 2.0
    assert best.metrics["score"] == 6.0
    assert best.metrics["base"] == 7        # train_loop_config merged in
    assert best.metrics["rank"] == 0        # metrics followed rank 0
    # Gangs fully torn down: all 4 worker CPUs are free again.
    @ray_tpu.remote
    def probe():
        return 1
    assert ray_tpu.get([probe.remote() for _ in range(4)]) == [1] * 4


@pytest.mark.timeout_s(300)
def test_tuner_trainer_pbt_exploits_gang_trials(ray_start_regular, tmp_path):
    """PBT over gang trials: a weak 2-worker trial clones a strong trial's
    orbax-persisted checkpoint and continues with perturbed config."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_loop(config):
        import json
        import os
        import tempfile
        import time

        from ray_tpu import train

        state = {"acc": 0.0}
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "s.json")) as f:
                state = json.load(f)
        for _ in range(10):
            time.sleep(0.05)
            state["acc"] += config["lr"]
            if train.get_world_rank() == 0:
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "s.json"), "w") as f:
                    json.dump(state, f)
                train.report({"acc": state["acc"]},
                             checkpoint=train.Checkpoint.from_directory(d))
            else:
                train.report({"acc": state["acc"]})

    scheduler = PopulationBasedTraining(
        metric="acc", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.01, 1.0]})
    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    tuner = Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.01, 1.0])},
        tune_config=TuneConfig(metric="acc", mode="max",
                               scheduler=scheduler),
    )
    grid = tuner.fit()
    assert not any(r.error for r in grid), [r.error for r in grid]
    best = grid.get_best_result()
    assert best.metrics["acc"] >= 5.0  # strong trial made progress
    # The weak trial (lr=0.01 start) either got exploited (acc jump far
    # beyond 10*0.01) or at minimum survived to completion.
    accs = sorted(r.metrics["acc"] for r in grid)
    assert accs[0] > 0.0


@pytest.mark.timeout_s(240)
def test_tuner_function_trial_bundle_resources(ray_start_regular):
    """A bundle LIST as resources_per_trial gives each function trial its
    own placement group — '1 trial CPU + 1 side CPU' is expressible
    (reference: PlacementGroupFactory)."""
    def trainable(config):
        from ray_tpu import train

        train.report({"score": config["x"]})

    tuner = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max",
                               max_concurrent_trials=2),
        resources_per_trial=[{"CPU": 1.0}, {"CPU": 1.0}],
    )
    grid = tuner.fit()
    assert not any(r.error for r in grid), [r.error for r in grid]
    assert grid.get_best_result().config["x"] == 2
    # Trial PGs removed: all 4 CPUs usable again.
    @ray_tpu.remote
    def probe():
        return 1
    assert ray_tpu.get([probe.remote() for _ in range(4)]) == [1] * 4


# ------------------------------------------------------- PB2 + median stop
# (VERDICT r4 Missing #7 / Next #10; reference: tune/schedulers/pb2.py,
# median_stopping_rule.py)


class _FakeTrial:
    def __init__(self, tid, config):
        self.id = tid
        self.config = config
        self.iteration = 0

    def __hash__(self):
        return hash(self.id)


def test_median_stopping_rule_stops_clear_loser():
    from ray_tpu.tune import MedianStoppingRule
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    rule = MedianStoppingRule(metric="loss", mode="min", grace_period=2,
                              min_samples_required=3)
    good1, good2, bad = (_FakeTrial("g1", {}), _FakeTrial("g2", {}),
                         _FakeTrial("b", {}))
    decisions = []
    for t in range(1, 6):
        rule.on_result(good1, {"loss": 1.0 / t, "training_iteration": t})
        rule.on_result(good2, {"loss": 1.2 / t, "training_iteration": t})
        decisions.append(
            rule.on_result(bad, {"loss": 5.0, "training_iteration": t}))
    assert decisions[0] == CONTINUE and decisions[1] == CONTINUE  # grace
    assert STOP in decisions[2:], decisions
    # A median-or-better trial is never stopped.
    assert all(
        rule.on_result(good1, {"loss": 0.01, "training_iteration": 9})
        == CONTINUE for _ in range(2))


def test_pb2_gp_guides_perturbation_toward_improving_region():
    from ray_tpu.tune import PB2

    pb2 = PB2(metric="score", mode="max", perturbation_interval=1,
              hyperparam_bounds={"lr": (1e-4, 1e-1)}, log_scale=["lr"],
              seed=0)
    hi = _FakeTrial("hi", {"lr": 5e-2})
    lo = _FakeTrial("lo", {"lr": 2e-4})
    # Reward rate proportional to lr: the GP should learn "high lr good".
    s_hi = s_lo = 0.0
    for t in range(1, 8):
        s_hi += 10.0
        s_lo += 0.1
        pb2.on_result(hi, {"score": s_hi, "training_iteration": t})
        pb2.on_result(lo, {"score": s_lo, "training_iteration": t})
    assert len(pb2._obs_y) >= 4
    picks = [pb2.perturb_config({"lr": 2e-4})["lr"] for _ in range(5)]
    # UCB should concentrate clearly above the geometric middle (3e-3).
    assert sum(p > 3e-3 for p in picks) >= 3, picks


def test_pb2_requires_bounds():
    from ray_tpu.tune import PB2

    with pytest.raises(ValueError):
        PB2(metric="score", mode="max")


@pytest.mark.timeout_s(240)
def test_pb2_sweep_exploits(ray_start_regular, tmp_path):
    """PB2 through the full Tuner: the bottom trial's exploit gets a
    GP-selected (in-bounds) lr instead of a random multiply."""
    from ray_tpu.tune import PB2

    def trainable(config):
        import json
        import os
        import tempfile
        import time

        from ray_tpu import tune as t

        state = {"acc": 0.0}
        ckpt = t.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "s.json")) as f:
                state = json.load(f)
        for _ in range(10):
            time.sleep(0.05)
            state["acc"] += config["lr"]
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "s.json"), "w") as f:
                json.dump(state, f)
            t.report({"acc": state["acc"]},
                     checkpoint=t.Checkpoint.from_directory(d))

    scheduler = PB2(metric="acc", mode="max", perturbation_interval=3,
                    hyperparam_bounds={"lr": (0.01, 1.0)},
                    log_scale=["lr"], seed=1)
    tuner = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.01, 1.0])},
        tune_config=TuneConfig(metric="acc", mode="max",
                               scheduler=scheduler),
        storage_path=str(tmp_path),
    )
    grid = tuner.fit()
    assert not any(r.error for r in grid), [r.error for r in grid]
    for r in grid:  # every (possibly exploited) config stayed in bounds
        assert 0.01 <= r.config["lr"] <= 1.0
