"""KV-cache decode correctness (VERDICT r4 Missing #2): prefill+decode must
reproduce the training-path forward exactly (same weights, same math, no
approximations), across GQA, padding, and sampling shapes."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def small_model():
    import jax

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=97, dim=32, n_layers=2, n_heads=4,
                            n_kv_heads=2, mlp_dim=64, max_seq_len=128)
    params = llama.init_params(cfg, jax.random.key(0))
    return cfg, params


def test_prefill_matches_forward(small_model):
    import jax

    from ray_tpu.models import llama, llama_decode

    cfg, params = small_model
    tokens = jax.random.randint(jax.random.key(1), (2, 10), 0,
                                cfg.vocab_size)
    full = llama.forward(params, tokens, cfg)         # (B, S, V)
    cache = llama_decode.init_cache(cfg, 2, 16)
    last, cache = llama_decode.prefill(params, tokens, cache, cfg)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               rtol=2e-2, atol=2e-2)
    assert int(cache["length"][0]) == 10


@pytest.mark.slow  # 18.9s: step-by-step re-forward; paged + spec
# bit-exactness tests keep decode parity in tier-1 (PR 16 rebudget)
def test_decode_step_matches_incremental_forward(small_model):
    """Greedy decode through the cache == greedy decode by re-running the
    full forward on the growing sequence (the no-cache oracle)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama, llama_decode

    cfg, params = small_model
    prompt = jax.random.randint(jax.random.key(2), (1, 6), 0,
                                cfg.vocab_size)

    # Oracle: argmax over full forward, re-run per token.
    seq = np.asarray(prompt)
    oracle = []
    for _ in range(5):
        logits = llama.forward(params, jnp.asarray(seq), cfg)
        nxt = int(jnp.argmax(logits[0, -1]))
        oracle.append(nxt)
        seq = np.concatenate([seq, [[nxt]]], axis=1)

    # Cache path: prefill once, then decode_step per token.
    cache = llama_decode.init_cache(cfg, 1, 32)
    logits, cache = llama_decode.prefill(params, prompt, cache, cfg)
    got = []
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    for _ in range(5):
        got.append(int(tok[0]))
        logits, cache = llama_decode.decode_step(params, cache, tok, cfg)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    assert got == oracle, (got, oracle)


def test_padded_prefill_ragged_lengths(small_model):
    """Right-padded rows of different lengths: each row's last-real-token
    logits match an unpadded forward of just that row."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama, llama_decode

    cfg, params = small_model
    r1 = jax.random.randint(jax.random.key(3), (1, 9), 0, cfg.vocab_size)
    r2 = jax.random.randint(jax.random.key(4), (1, 4), 0, cfg.vocab_size)
    padded = jnp.zeros((2, 9), jnp.int32)
    padded = padded.at[0].set(r1[0])
    padded = padded.at[1, :4].set(r2[0])
    lengths = jnp.array([9, 4], jnp.int32)

    cache = llama_decode.init_cache(cfg, 2, 16)
    last, cache = llama_decode.prefill(params, padded, cache, cfg,
                                       lengths=lengths)
    solo1 = llama.forward(params, r1, cfg)[0, -1]
    solo2 = llama.forward(params, r2, cfg)[0, -1]
    np.testing.assert_allclose(np.asarray(last[0]), np.asarray(solo1),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(last[1]), np.asarray(solo2),
                               rtol=2e-2, atol=2e-2)
    # Decode continues each row at ITS OWN position.
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    _, cache = llama_decode.decode_step(params, cache, tok, cfg)
    assert list(np.asarray(cache["length"])) == [10, 5]


def test_generate_greedy_deterministic(small_model):
    from ray_tpu.models import llama_decode

    cfg, params = small_model
    prompt = np.array([[5, 17, 3]], np.int32)
    out1 = np.asarray(llama_decode.generate(params, prompt, cfg,
                                            max_new_tokens=6))
    out2 = np.asarray(llama_decode.generate(params, prompt, cfg,
                                            max_new_tokens=6))
    assert out1.shape == (1, 6)
    np.testing.assert_array_equal(out1, out2)
    assert (out1 >= 0).all() and (out1 < cfg.vocab_size).all()


def test_generate_eos_padding(small_model):
    """After a row samples eos, every later token is eos (the stream is
    closed — serving relies on this to free the slot)."""
    import jax

    from ray_tpu.models import llama, llama_decode

    cfg, params = small_model
    prompt = np.array([[1, 2]], np.int32)
    greedy = np.asarray(llama_decode.generate(params, prompt, cfg,
                                              max_new_tokens=8))
    eos = int(greedy[0, 2])  # force eos at the 3rd generated token
    out = np.asarray(llama_decode.generate(params, prompt, cfg,
                                           max_new_tokens=8, eos_id=eos))
    hit = np.where(out[0] == eos)[0]
    assert len(hit) > 0
    first = hit[0]
    assert (out[0, first:] == eos).all()


def test_gqa_cache_width(small_model):
    """The cache is allocated at KV-head width (the GQA bandwidth win)."""
    from ray_tpu.models import llama_decode

    cfg, params = small_model
    cache = llama_decode.init_cache(cfg, 3, 64)
    assert cache["k"].shape == (cfg.n_layers, 3, 64, cfg.n_kv_heads,
                                cfg.head_dim)
    assert llama_decode.cache_bucket(100) == 128
    assert llama_decode.cache_bucket(129) == 256
