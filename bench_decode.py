"""Decode serving benchmark: KV-cache generation rows for BENCH_SERVE.json
(VERDICT r4 Next #3 — "Re-measure BENCH_SERVE with decode tokens/s and
per-token p50").

Measures on the attached chip, 160M-param Llama:

  1. engine-direct continuous batching (slots=16): decode tokens/s,
     inter-token p50/p99, TTFT p50 — per-token steps (decode_chunk=1);
  2. same with decode_chunk=8 (K greedy steps per device call): the
     dispatch-floor amortization row (this rig has a ~60 ms tunnel floor
     per device call, so chunking is the serving lever here);
  3. the full serve stack: deployment replica + handle, closed-loop
     clients requesting generation (streamed tokens).

Appends/replaces the decode rows in BENCH_SERVE.json, preserving the
prefill rows. Run: ``python bench_decode.py [--quick]``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import threading
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/ray_tpu_bench_jax_cache")


def pctl(xs, p):
    """Nearest-rank percentile: the value at 1-indexed rank ceil(p*n).
    The old ``int(len(xs) * p)`` index was biased one rank high (p50 of
    an even-sized sample read above the median; p100 depended on the
    min() clamp), which skews small-sample p50/p99 rows."""
    xs = sorted(xs)
    return xs[max(0, min(len(xs) - 1, math.ceil(p * len(xs)) - 1))]


def engine_rows(params, cfg, quick: bool):
    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    slots = 4 if quick else 16
    prompt_len = 16 if quick else 64
    gen = 16 if quick else 64
    n_requests = 8 if quick else 64
    rows = []
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]

    for chunk in (1, 8):
        # Prefix cache off: this workload is zero-share random prompts
        # (every insert would be futile) — the shared_prefix section is
        # the one that measures the cache.
        eng = DecodeEngine(params, cfg, slots=slots,
                           capacity=256, decode_chunk=chunk,
                           prefix_pool_entries=0)
        # Warm every program before timing: each admission batch size
        # (n = 1..slots, powers of two), the decode step, and (for
        # chunked mode) the whole k ladder — a solo request's
        # remaining-count walks down through all of k=chunk..1.
        w = eng.submit(prompts[0], max_new_tokens=max(2, 2 * chunk))
        while not w.done.is_set():
            eng.step()
        n_warm = 2
        while n_warm <= slots:
            burst = [eng.submit(prompts[i % len(prompts)],
                                max_new_tokens=1) for i in range(n_warm)]
            while not all(b.done.is_set() for b in burst):
                eng.step()
            n_warm *= 2

        t0 = time.monotonic()
        reqs = [eng.submit(p, max_new_tokens=gen)
                for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            if eng.step() == 0:
                time.sleep(0.001)
        wall = time.monotonic() - t0
        total_tokens = sum(len(r.output) for r in reqs)
        # Per-token latency per request: stream duration / tokens (robust
        # to chunked emission's bursts, which make raw gaps bimodal).
        per_tok = [1e3 * (r.finished_at - r.first_token_at)
                   / max(1, len(r.output) - 1) for r in reqs
                   if len(r.output) > 1]
        ttfts = [1e3 * (r.first_token_at - r.submitted_at) for r in reqs]
        rows.append({
            "metric": f"decode_tokens_per_s_chunk{chunk}",
            "value": round(total_tokens / wall, 1),
            "unit": "tokens/s",
            "note": (f"{n_requests} reqs x {gen} new tokens, prompt "
                     f"{prompt_len}, {slots} slots continuous batching, "
                     f"decode_chunk={chunk}; wall {wall:.1f}s"),
        })
        rows.append({
            "metric": f"decode_per_token_p50_chunk{chunk}",
            "value": round(pctl(per_tok, 0.5), 1) if per_tok else None,
            "unit": "ms",
            "note": (f"per-request stream duration/token; p99="
                     f"{pctl(per_tok, 0.99):.1f}ms; TTFT p50="
                     f"{pctl(ttfts, 0.5):.0f}ms (includes queueing — "
                     f"{n_requests} reqs over {slots} slots)"
                     if per_tok else ""),
        })
        eng.shutdown()
    return rows


def shared_prefix_rows(params, cfg, quick: bool, platform: str):
    """Shared-prefix workload (hot system prompt): TTFT with the prefix
    KV cache off vs on, plus hit rate and prefill tokens saved. Models
    RLAX-style rollout generation / templated chat traffic where >=50%
    of every prompt is a shared prefix."""
    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    # shared_len sits ON the power-of-two insert grid so the pool entry
    # covers exactly the shared region (prefix_capacity = capacity//2).
    slots = 4 if quick else 8
    shared_len = 32 if quick else 128
    suffix_len = 12 if quick else 32
    gen = 4 if quick else 4
    n_requests = 8 if quick else 32
    capacity = 128 if quick else 256
    rng = np.random.default_rng(2)
    shared = rng.integers(0, cfg.vocab_size, shared_len).tolist()
    prompts = [shared + rng.integers(0, cfg.vocab_size,
                                     suffix_len).tolist()
               for _ in range(n_requests)]
    prompt_len = shared_len + suffix_len

    results = {}
    for mode, entries in (("off", 0), ("on", 8)):
        eng = DecodeEngine(params, cfg, slots=slots, capacity=capacity,
                           prefix_pool_entries=entries,
                           prefix_match_min_tokens=16)
        # Warm every program (admission n ladder, both prefill paths,
        # decode step) AND the prefix pool itself: the row measures
        # steady-state serving of a hot prefix, not the cold insert.
        w = eng.submit(prompts[0], max_new_tokens=2)
        while not w.done.is_set():
            eng.step()
        n_warm = 1
        while n_warm <= slots:
            burst = [eng.submit(prompts[i % len(prompts)],
                                max_new_tokens=1) for i in range(n_warm)]
            while not all(b.done.is_set() for b in burst):
                eng.step()
            n_warm *= 2
        pre = eng.prefix.stats() if eng.prefix is not None else None

        reqs = [eng.submit(p, max_new_tokens=gen) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            if eng.step() == 0:
                time.sleep(0.001)
        ttfts = [1e3 * (r.first_token_at - r.submitted_at) for r in reqs]
        stats = {"p50": pctl(ttfts, 0.5), "p99": pctl(ttfts, 0.99)}
        if pre is not None:
            post = eng.prefix.stats()
            queries = post["queries"] - pre["queries"]
            hits = post["hits"] - pre["hits"]
            stats["hit_rate"] = hits / max(1, queries)
            stats["tokens_saved"] = (post["prefill_tokens_saved"]
                                     - pre["prefill_tokens_saved"])
        eng.shutdown()
        results[mode] = stats

    speedup = results["off"]["p50"] / max(1e-9, results["on"]["p50"])
    workload = (f"{n_requests} reqs, prompt {prompt_len} "
                f"({shared_len} shared / {100 * shared_len // prompt_len}%"
                f"), {gen} new tokens, {slots} slots; {platform}")
    return [
        {
            "metric": "decode_shared_prefix_ttft_p50_off",
            "value": round(results["off"]["p50"], 1),
            "unit": "ms",
            "note": (f"prefix cache OFF; p99="
                     f"{results['off']['p99']:.1f}ms; {workload}"),
        },
        {
            "metric": "decode_shared_prefix_ttft_p50_on",
            "value": round(results["on"]["p50"], 1),
            "unit": "ms",
            "note": (f"prefix cache ON (suffix-only prefill); p99="
                     f"{results['on']['p99']:.1f}ms; {speedup:.1f}x TTFT "
                     f"p50 vs off; {workload}"),
        },
        {
            "metric": "decode_prefix_hit_rate",
            "value": round(100 * results["on"]["hit_rate"], 1),
            "unit": "%",
            "note": (f"prefix-cache hits / admissions over the timed "
                     f"workload (warm pool); {workload}"),
        },
        {
            "metric": "decode_prefix_prefill_tokens_saved",
            "value": int(results["on"]["tokens_saved"]),
            "unit": "tokens",
            "note": (f"prompt tokens spliced from the prefix pool "
                     f"instead of re-prefilled; {workload}"),
        },
    ]


def serve_stack_row(cfg, quick: bool):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.decode import LlamaDecodeDeployment

    import numpy as np

    gen = 8 if quick else 32
    clients = 2 if quick else 8
    duration = 5 if quick else 20
    dep = serve.deployment(LlamaDecodeDeployment).options(
        max_ongoing_requests=64, max_concurrency=32,
        ray_actor_options=(
            {} if quick else {"resources": {"TPU": 1.0}}),
    ).bind(config=cfg, slots=4 if quick else 16, capacity=256,
           decode_chunk=8)
    serve.run(dep, name="llm_decode")
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if serve.status().get("llm_decode", {}).get("replicas", 0) >= 1:
            break
        time.sleep(0.5)
    handle = serve.get_deployment_handle("llm_decode")
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 16 if quick else 64).tolist()
    # Warm (retry through the replica-registration race).
    for _ in range(120):
        try:
            handle.remote({"tokens": prompt, "max_new_tokens": 2}).result(
                timeout=300)
            break
        except RuntimeError:
            time.sleep(1.0)

    stop = time.monotonic() + duration
    lat, tokens = [], [0]
    lock = threading.Lock()

    def client():
        while time.monotonic() < stop:
            t0 = time.monotonic()
            out = handle.remote({"tokens": prompt,
                                 "max_new_tokens": gen}).result(
                timeout=300)
            dt = time.monotonic() - t0
            with lock:
                lat.append(dt * 1e3)
                tokens[0] += len(out["tokens"])

    threads = [threading.Thread(target=client) for _ in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    row = {
        "metric": "decode_serve_stack_tokens_per_s",
        "value": round(tokens[0] / wall, 1),
        "unit": "tokens/s",
        "note": (f"{clients} closed-loop clients x {gen} new tokens/req "
                 f"through controller-routed handle, {len(lat)} reqs, "
                 f"req p50={pctl(lat, 0.5):.0f}ms "
                 f"p99={pctl(lat, 0.99):.0f}ms"),
    }
    serve.shutdown()
    return [row]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--sections", default="engine,serve,shared_prefix",
        help="comma-set of row groups to (re)measure: engine, serve, "
             "shared_prefix. Only the selected groups' rows are "
             "replaced in BENCH_SERVE.json; the rest are preserved.")
    parser.add_argument(
        "--model", default=None,
        help="llama preset override (default: debug if --quick else "
             "160m)")
    parser.add_argument(
        "--cpu", action="store_true",
        help="force JAX_PLATFORMS=cpu but still write BENCH_SERVE.json "
             "(rows are annotated with the platform)")
    args = parser.parse_args()
    sections = {s.strip() for s in args.sections.split(",") if s.strip()}

    import jax

    if args.quick or args.cpu:
        # Env var too: serve replica workers inherit it at fork, so the
        # whole quick path (driver + replicas) stays on CPU.
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import ray_tpu
    from ray_tpu.models import llama

    preset = args.model or ("debug" if args.quick else "160m")
    cfg = llama.PRESETS[preset]
    params = llama.init_params(cfg, jax.random.key(0))
    platform = jax.devices()[0].platform
    plat_note = f"{preset} model, {platform} backend"

    rows = []
    if "engine" in sections:
        rows += engine_rows(params, cfg, args.quick)
    if "shared_prefix" in sections:
        rows += shared_prefix_rows(params, cfg, args.quick, plat_note)
    if "serve" in sections:
        ray_tpu.init(num_cpus=4)
        try:
            rows += serve_stack_row(cfg, args.quick)
        finally:
            ray_tpu.shutdown()

    out_path = "BENCH_SERVE.json"
    doc = {"artifact": "BENCH_SERVE", "rows": []}
    if os.path.exists(out_path) and not args.quick:
        with open(out_path) as f:
            doc = json.load(f)
        # Replace exactly the rows this run re-measured.
        emitted = {r["metric"] for r in rows}
        doc["rows"] = [r for r in doc.get("rows", [])
                       if r["metric"] not in emitted]
    if args.quick:
        out_path = "/tmp/bench_decode_quick.json"
    doc.setdefault("decode_model",
                   "llama-160m, KV-cache continuous batching "
                   "(serve/decode.py), bf16")
    doc["rows"] = doc.get("rows", []) + rows
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(rows))


if __name__ == "__main__":
    main()
