"""Decode serving benchmark: KV-cache generation rows for BENCH_SERVE.json
(VERDICT r4 Next #3 — "Re-measure BENCH_SERVE with decode tokens/s and
per-token p50").

Measures on the attached chip, 160M-param Llama:

  1. engine-direct continuous batching (slots=16): decode tokens/s,
     inter-token p50/p99, TTFT p50 — per-token steps (decode_chunk=1);
  2. same with decode_chunk=8 (K greedy steps per device call): the
     dispatch-floor amortization row (this rig has a ~60 ms tunnel floor
     per device call, so chunking is the serving lever here);
  3. the full serve stack: deployment replica + handle, closed-loop
     clients requesting generation (streamed tokens).

Appends/replaces the decode rows in BENCH_SERVE.json, preserving the
prefill rows. Run: ``python bench_decode.py [--quick]``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import threading
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/ray_tpu_bench_jax_cache")


def pctl(xs, p):
    """Nearest-rank percentile: the value at 1-indexed rank ceil(p*n).
    The old ``int(len(xs) * p)`` index was biased one rank high (p50 of
    an even-sized sample read above the median; p100 depended on the
    min() clamp), which skews small-sample p50/p99 rows."""
    xs = sorted(xs)
    return xs[max(0, min(len(xs) - 1, math.ceil(p * len(xs)) - 1))]


def hist_pctl_ms(deployment: str, metric: str, p: float,
                 aggregated=None):
    """Percentile (ms) of a serve SLO histogram for one deployment —
    the bench reads the SAME instruments production scrapes instead of
    keeping its own ad-hoc latency lists. Values are bucket-
    interpolated (Prometheus histogram_quantile semantics), so they
    are quantized to the bucket grid. ``aggregated=None`` reads this
    process's registry; pass a ``list_metrics`` result for
    cluster-side (replica) histograms."""
    from ray_tpu.util.metrics import _Registry, histogram_quantile, \
        merge_histograms

    if aggregated is None:
        aggregated = {"local": _Registry.get().snapshot()}
    merged = merge_histograms(aggregated, metric)
    entry = merged.get((("deployment", deployment),))
    if entry is None or not entry["count"]:
        return None
    return histogram_quantile(entry, p) * 1e3


def engine_rows(params, cfg, quick: bool, platform: str = ""):
    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    slots = 4 if quick else 16
    prompt_len = 16 if quick else 64
    gen = 16 if quick else 64
    n_requests = 8 if quick else 64
    rows = []
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]

    for chunk in (1, 8):
        # Prefix cache off: this workload is zero-share random prompts
        # (every insert would be futile) — the shared_prefix section is
        # the one that measures the cache.
        eng = DecodeEngine(params, cfg, slots=slots,
                           capacity=256, decode_chunk=chunk,
                           prefix_pool_entries=0,
                           metrics_deployment=f"warmup_chunk{chunk}")
        # Warm every program before timing: each admission batch size
        # (n = 1..slots, powers of two), the decode step, and (for
        # chunked mode) the whole k ladder — a solo request's
        # remaining-count walks down through all of k=chunk..1.
        w = eng.submit(prompts[0], max_new_tokens=max(2, 2 * chunk))
        while not w.done.is_set():
            eng.step()
        n_warm = 2
        while n_warm <= slots:
            burst = [eng.submit(prompts[i % len(prompts)],
                                max_new_tokens=1) for i in range(n_warm)]
            while not all(b.done.is_set() for b in burst):
                eng.step()
            n_warm *= 2

        # Warmup compiles recorded under warmup_chunk*; the measured
        # requests observe under the row's own label (terminal-step
        # labeling), so compile time never skews the percentile rows.
        eng.set_metrics_deployment(f"bench_chunk{chunk}")
        t0 = time.monotonic()
        reqs = [eng.submit(p, max_new_tokens=gen)
                for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            if eng.step() == 0:
                time.sleep(0.001)
        wall = time.monotonic() - t0
        total_tokens = sum(len(r.output) for r in reqs)
        # Percentiles from the serve SLO HISTOGRAMS the engine records
        # (serve/metrics.py: inter-token = per-request stream duration
        # / token, robust to chunked emission's bursts) — the bench
        # reads the production instruments instead of ad-hoc lists, so
        # a bench row and a Prometheus scrape can never disagree.
        dep = f"bench_chunk{chunk}"
        tok_p50 = hist_pctl_ms(dep, "serve_inter_token_s", 0.5)
        tok_p99 = hist_pctl_ms(dep, "serve_inter_token_s", 0.99)
        ttft_p50 = hist_pctl_ms(dep, "serve_ttft_s", 0.5)
        rows.append({
            "metric": f"decode_tokens_per_s_chunk{chunk}",
            "value": round(total_tokens / wall, 1),
            "unit": "tokens/s",
            "note": (f"{n_requests} reqs x {gen} new tokens, prompt "
                     f"{prompt_len}, {slots} slots continuous batching, "
                     f"decode_chunk={chunk}; wall {wall:.1f}s; "
                     f"{platform}"),
        })
        rows.append({
            "metric": f"decode_per_token_p50_chunk{chunk}",
            "value": round(tok_p50, 1) if tok_p50 is not None else None,
            "unit": "ms",
            "note": (f"per-request stream duration/token; p99="
                     f"{tok_p99:.1f}ms; TTFT p50={ttft_p50:.0f}ms "
                     f"(includes queueing — {n_requests} reqs over "
                     f"{slots} slots); from serve_inter_token_s/"
                     f"serve_ttft_s histograms (bucket-interpolated "
                     f"pctl); {platform}"
                     if tok_p50 is not None else ""),
        })
        eng.shutdown()
    return rows


def shared_prefix_rows(params, cfg, quick: bool, platform: str):
    """Shared-prefix workload (hot system prompt): TTFT with the prefix
    KV cache off vs on, plus hit rate and prefill tokens saved. Models
    RLAX-style rollout generation / templated chat traffic where >=50%
    of every prompt is a shared prefix."""
    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    # shared_len sits ON the power-of-two insert grid so the pool entry
    # covers exactly the shared region (prefix_capacity = capacity//2).
    slots = 4 if quick else 8
    shared_len = 32 if quick else 128
    suffix_len = 12 if quick else 32
    gen = 4 if quick else 4
    n_requests = 8 if quick else 32
    capacity = 128 if quick else 256
    rng = np.random.default_rng(2)
    shared = rng.integers(0, cfg.vocab_size, shared_len).tolist()
    prompts = [shared + rng.integers(0, cfg.vocab_size,
                                     suffix_len).tolist()
               for _ in range(n_requests)]
    prompt_len = shared_len + suffix_len

    results = {}
    for mode, entries in (("off", 0), ("on", 8)):
        eng = DecodeEngine(params, cfg, slots=slots, capacity=capacity,
                           prefix_pool_entries=entries,
                           prefix_match_min_tokens=16)
        # Warm every program (admission n ladder, both prefill paths,
        # decode step) AND the prefix pool itself: the row measures
        # steady-state serving of a hot prefix, not the cold insert.
        w = eng.submit(prompts[0], max_new_tokens=2)
        while not w.done.is_set():
            eng.step()
        n_warm = 1
        while n_warm <= slots:
            burst = [eng.submit(prompts[i % len(prompts)],
                                max_new_tokens=1) for i in range(n_warm)]
            while not all(b.done.is_set() for b in burst):
                eng.step()
            n_warm *= 2
        pre = eng.prefix.stats() if eng.prefix is not None else None

        reqs = [eng.submit(p, max_new_tokens=gen) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            if eng.step() == 0:
                time.sleep(0.001)
        ttfts = [1e3 * (r.first_token_at - r.submitted_at) for r in reqs]
        stats = {"p50": pctl(ttfts, 0.5), "p99": pctl(ttfts, 0.99)}
        if pre is not None:
            post = eng.prefix.stats()
            queries = post["queries"] - pre["queries"]
            hits = post["hits"] - pre["hits"]
            stats["hit_rate"] = hits / max(1, queries)
            stats["tokens_saved"] = (post["prefill_tokens_saved"]
                                     - pre["prefill_tokens_saved"])
        eng.shutdown()
        results[mode] = stats

    speedup = results["off"]["p50"] / max(1e-9, results["on"]["p50"])
    workload = (f"{n_requests} reqs, prompt {prompt_len} "
                f"({shared_len} shared / {100 * shared_len // prompt_len}%"
                f"), {gen} new tokens, {slots} slots; {platform}")
    return [
        {
            "metric": "decode_shared_prefix_ttft_p50_off",
            "value": round(results["off"]["p50"], 1),
            "unit": "ms",
            "note": (f"prefix cache OFF; p99="
                     f"{results['off']['p99']:.1f}ms; {workload}"),
        },
        {
            "metric": "decode_shared_prefix_ttft_p50_on",
            "value": round(results["on"]["p50"], 1),
            "unit": "ms",
            "note": (f"prefix cache ON (suffix-only prefill); p99="
                     f"{results['on']['p99']:.1f}ms; {speedup:.1f}x TTFT "
                     f"p50 vs off; {workload}"),
        },
        {
            "metric": "decode_prefix_hit_rate",
            "value": round(100 * results["on"]["hit_rate"], 1),
            "unit": "%",
            "note": (f"prefix-cache hits / admissions over the timed "
                     f"workload (warm pool); {workload}"),
        },
        {
            "metric": "decode_prefix_prefill_tokens_saved",
            "value": int(results["on"]["tokens_saved"]),
            "unit": "tokens",
            "note": (f"prompt tokens spliced from the prefix pool "
                     f"instead of re-prefilled; {workload}"),
        },
    ]


def overload_rows(params, cfg, quick: bool, platform: str):
    """Load-shedding behavior at 2x slot capacity (ISSUE 3):
    ``2 * slots`` closed-loop clients against a small pending-queue cap,
    vs a ``slots``-client non-overloaded baseline measured the same way.
    Shed clients honor ``Retry-After`` (bounded). The cap is deliberately
    tight (``max(1, slots // 4)``): under sustained overload ANY queue
    depth converts straight into accepted-request TTFT (Little's law),
    so the engine sheds the excess in <1 ms and keeps the queue — and
    therefore accepted latency — short. Rows record shed-rejection p99
    (bar: < 50 ms), accepted TTFT p99 vs baseline (bar: < 1.5x), and the
    max observed queue depth (bar: never exceeds queue_max)."""
    import threading

    from ray_tpu.core.errors import OverloadedError
    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    slots = 4 if quick else 8
    prompt_len = 16 if quick else 32
    gen = 8 if quick else 16
    duration = 6.0 if quick else 25.0
    queue_max = max(1, slots // 8)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(4 * slots)]

    eng = DecodeEngine(params, cfg, slots=slots, capacity=128,
                       prefix_pool_entries=0, queue_max=4 * slots)
    # Warm the program ladder (loose cap: warm bursts queue up before
    # the manual step loop drains them).
    w = eng.submit(prompts[0], max_new_tokens=2)
    while not w.done.is_set():
        eng.step()
    n_warm = 2
    while n_warm <= slots:
        burst = [eng.submit(prompts[i], max_new_tokens=1)
                 for i in range(n_warm)]
        while not all(b.done.is_set() for b in burst):
            eng.step()
        n_warm *= 2
    eng.queue_max = queue_max  # the measured configuration

    loop = threading.Thread(target=eng.serve_forever, daemon=True)
    loop.start()

    def run_phase(n_clients: int, phase_s: float):
        ttfts: list = []
        sheds: list = []
        stop = time.monotonic() + phase_s
        max_queue = [0]

        def client(ci: int) -> None:
            # Varied generation lengths (gen/2 .. 3*gen/2): equal
            # lengths complete in synchronized waves, which makes every
            # queued request wait a FULL generation for a slot — an
            # artifact no real traffic mix has.
            crng = np.random.default_rng(100 + ci)
            while time.monotonic() < stop:
                t0 = time.perf_counter()
                n_new = int(crng.integers(max(1, gen // 2),
                                          gen + gen // 2 + 1))
                try:
                    req = eng.submit(prompts[ci % len(prompts)],
                                     max_new_tokens=n_new)
                except OverloadedError as e:
                    sheds.append(1e3 * (time.perf_counter() - t0))
                    time.sleep(min(e.retry_after_s, 0.25))
                    continue
                req.done.wait()
                if req.first_token_at is not None:
                    ttfts.append(1e3 * (req.first_token_at
                                        - req.submitted_at))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        while any(t.is_alive() for t in threads):
            max_queue[0] = max(max_queue[0], eng.stats()["queued"])
            time.sleep(0.01)
        return ttfts, sheds, max_queue[0]

    base_ttft, _, _ = run_phase(slots, duration)
    accepted, shed_lat, max_queue = run_phase(2 * slots, duration)
    eng.shutdown()
    loop.join(timeout=5)

    workload = (f"closed-loop {2 * slots} clients / {slots} slots for "
                f"{duration:.0f}s, queue_max={queue_max}, prompt "
                f"{prompt_len}, {gen} new tokens; {platform}")
    base_p99 = pctl(base_ttft, 0.99) if base_ttft else float("nan")
    acc_p99 = pctl(accepted, 0.99) if accepted else None
    return [
        {
            "metric": "decode_overload_shed_rejection_p99",
            "value": round(pctl(shed_lat, 0.99), 3) if shed_lat else None,
            "unit": "ms",
            "note": (f"submit()->OverloadedError latency over "
                     f"{len(shed_lat)} shed requests (p50="
                     f"{pctl(shed_lat, 0.5):.3f}ms); bar <50ms; "
                     f"{workload}" if shed_lat else workload),
        },
        {
            "metric": "decode_overload_accepted_ttft_p99",
            "value": round(acc_p99, 1) if acc_p99 is not None else None,
            "unit": "ms",
            "note": (f"TTFT p99 of {len(accepted)} ACCEPTED requests at "
                     f"2x offered load = "
                     f"{acc_p99 / max(1e-9, base_p99):.2f}x the "
                     f"non-overloaded closed-loop baseline p99 "
                     f"({base_p99:.1f}ms, {len(base_ttft)} reqs); max "
                     f"pending-queue depth observed {max_queue} (cap "
                     f"{queue_max}); {workload}"
                     if acc_p99 is not None else workload),
        },
    ]


def paged_rows(quick: bool, platform: str):
    """Paged-KV rows (ISSUE 6): (a) concurrency per pool byte — active
    requests sustained in the same pool bytes vs whole-row capacity
    (acceptance bar >= 1.5x, also asserted in tests/test_paged_kv.py);
    (b) mixed 64/512/4k prompt mix, chunked-prefill ON vs OFF: TTFT p99
    and per-token p99 (the un-chunked baseline is one monolithic prefill
    per admission — every active stream stalls for its duration);
    (c) tokens/s/slot and HBM pool bytes per active request.

    Uses a dedicated small config with a long rope table (the preset
    debug model caps max_seq_len at 128; 4k prompts need 8k)."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    cfg = llama.LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_seq_len=2048 if quick else 8192)
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    T = 64
    rows = []

    # ---- (a) + (c): overcommitted pool concurrency, same pool bytes
    slots, capacity, pool_pages = 16, 1024, 8 * 1024 // T
    whole_rows = pool_pages * T // capacity  # 8
    eng = DecodeEngine(params, cfg, slots=slots, capacity=capacity,
                       page_tokens=T, pool_pages=pool_pages,
                       prefix_pool_entries=0)
    pool_bytes = int(eng.cache["k"].nbytes + eng.cache["v"].nbytes)
    prompts = [rng.integers(0, cfg.vocab_size, 70).tolist()
               for _ in range(slots)]
    warm = [eng.submit(p, max_new_tokens=2) for p in prompts]
    while not all(w.done.is_set() for w in warm):
        eng.step()
    t0 = time.monotonic()
    gen = 16 if quick else 48
    reqs = [eng.submit(p, max_new_tokens=gen) for p in prompts]
    eng.step()
    active = eng.stats()["active"]
    while not all(r.done.is_set() for r in reqs):
        eng.step()
    wall = time.monotonic() - t0
    total = sum(len(r.output) for r in reqs)
    rows.append({
        "metric": "decode_paged_concurrency_gain",
        "value": round(active / whole_rows, 2),
        "unit": "x",
        "note": (f"{active} concurrent active requests in a pool whose "
                 f"bytes hold {whole_rows} whole {capacity}-token rows "
                 f"(kv_page_tokens={T}, {pool_pages} pages, "
                 f"{pool_bytes / 1e6:.1f} MB pool); bar >= 1.5x; "
                 f"prompt 70 + {gen} new; {platform}"),
    })
    rows.append({
        "metric": "decode_paged_pool_bytes_per_request",
        "value": round(pool_bytes / active / 1e6, 3),
        "unit": "MB",
        "note": (f"KV pool bytes / {active} active requests (whole-row "
                 f"equivalent: {pool_bytes / whole_rows / 1e6:.3f} MB); "
                 f"{platform}"),
    })
    rows.append({
        "metric": "decode_paged_tokens_per_s_per_slot",
        "value": round(total / wall / active, 2),
        "unit": "tokens/s/slot",
        "note": (f"{total} tokens over {wall:.1f}s across {active} "
                 f"paged slots (tiny 2-layer model; the row tracks the "
                 f"paged-vs-whole-row regression, not absolute speed); "
                 f"{platform}"),
    })
    eng.shutdown()

    # ---- (b): mixed prompt mix, chunked prefill ON vs OFF
    mix = ([32, 32, 128, 128, 512] if quick
           else [64, 64, 64, 512, 512, 4096])
    gen = 8 if quick else 24
    capacity = 1024 if quick else 4352  # 4096 + headroom, % 64 == 0
    chunk = 128 if quick else 256
    results = {}
    for mode, chunk_tok in (("monolithic", 0), ("chunked", chunk)):
        eng = DecodeEngine(params, cfg, slots=4, capacity=capacity,
                           page_tokens=T, prefix_pool_entries=0,
                           prefill_chunk_tokens=chunk_tok)
        # Warm every program in the mix (compile outside the window).
        warm = [eng.submit(rng.integers(0, cfg.vocab_size, n).tolist(),
                           max_new_tokens=2) for n in set(mix)]
        while not all(w.done.is_set() for w in warm):
            eng.step()
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist()
                   for n in mix]
        t0 = time.monotonic()
        reqs = [eng.submit(p, max_new_tokens=gen) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            if eng.step() == 0:
                time.sleep(0.001)
        wall = time.monotonic() - t0
        ttfts = [1e3 * (r.first_token_at - r.submitted_at) for r in reqs]
        per_tok = [1e3 * (r.finished_at - r.first_token_at)
                   / max(1, len(r.output) - 1) for r in reqs
                   if len(r.output) > 1]
        results[mode] = {
            "ttft_p99": pctl(ttfts, 0.99),
            "per_tok_p99": pctl(per_tok, 0.99),
            "wall": wall,
            "chunks": eng.prefill_chunks,
        }
        eng.shutdown()
    workload = (f"{len(mix)} reqs, prompt mix {sorted(set(mix))}, "
                f"{gen} new tokens, 4 paged slots (T={T}); {platform}")
    rows.append({
        "metric": "decode_paged_mixed_ttft_p99_monolithic",
        "value": round(results["monolithic"]["ttft_p99"], 1),
        "unit": "ms",
        "note": (f"chunked prefill OFF (one monolithic prefill per "
                 f"admission); per-token p99="
                 f"{results['monolithic']['per_tok_p99']:.1f}ms; "
                 f"{workload}"),
    })
    rows.append({
        "metric": "decode_paged_mixed_ttft_p99_chunked",
        "value": round(results["chunked"]["ttft_p99"], 1),
        "unit": "ms",
        "note": (f"chunked prefill ON (prefill_chunk_tokens={chunk}, "
                 f"{results['chunked']['chunks']} chunks interleaved); "
                 f"per-token p99="
                 f"{results['chunked']['per_tok_p99']:.1f}ms vs "
                 f"{results['monolithic']['per_tok_p99']:.1f}ms "
                 f"un-chunked — a long admission stalls active streams "
                 f"for at most one chunk; {workload}"),
    })
    rows.append({
        "metric": "decode_paged_mixed_per_token_p99_chunked",
        "value": round(results["chunked"]["per_tok_p99"], 1),
        "unit": "ms",
        "note": (f"inter-token p99 of ACTIVE streams while 4k-class "
                 f"prefills interleave (un-chunked baseline "
                 f"{results['monolithic']['per_tok_p99']:.1f}ms); "
                 f"{workload}"),
    })
    return rows


def spec_rows(quick: bool, platform: str):
    """Speculative-decoding rows (ISSUE 16): accept-rate x tokens/s per
    prompt mix, bracketed by the two draft extremes reachable with
    random weights — a SELF-draft (the target proposes for itself, so
    acceptance ~= 1.0 and the row isolates the verify-batching /
    dispatch-amortization ceiling) and a tiny independent draft
    (acceptance ~= 0 on random weights: the pure-overhead floor). A
    trained draft lands between the brackets. Sampled (temp 0.8) rows
    measure the documented fallback: spec disengages (argmax acceptance
    rule) and the fused device sampler carries the batch. Plus the
    donated-buffer / device-sampler step-time delta row. CPU-host
    caveats: BENCH_NOTES.md."""
    import jax
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.decode import DecodeEngine

    cfg = llama.LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        mlp_dim=128, max_seq_len=2048 if quick else 8192)
    params = llama.init_params(cfg, jax.random.key(0))
    draft_cfg = llama.LlamaConfig(
        vocab_size=256, dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
        mlp_dim=64, max_seq_len=cfg.max_seq_len)
    draft_params = llama.init_params(draft_cfg, jax.random.key(1))
    rng = np.random.default_rng(0)
    T, slots, k = 64, 4, 4
    gen = 12 if quick else 32
    mixes = [("64", 64), ("512", 512)]
    if not quick:
        mixes.append(("4k", 4096))
    rows = []

    def run(mix_len, temperature=0.0, draft=None, sampler=False):
        capacity = 1 << (mix_len + gen + k + 1).bit_length()
        capacity = min(capacity, cfg.max_seq_len)
        kw = dict(page_tokens=T,
                  pool_pages=slots * (capacity // T) + 1,
                  prefix_pool_entries=0, device_sampler=sampler)
        if draft is not None:
            kw.update(spec_draft_params=draft[0],
                      spec_draft_config=draft[1], spec_k=k)
        eng = DecodeEngine(params, cfg, slots=slots,
                           capacity=capacity, **kw)
        prompts = [rng.integers(0, cfg.vocab_size, mix_len).tolist()
                   for _ in range(slots)]
        warm = [eng.submit(p, max_new_tokens=2,
                           temperature=temperature) for p in prompts]
        while not all(w.done.is_set() for w in warm):
            eng.step()
        t0 = time.monotonic()
        reqs = [eng.submit(p, max_new_tokens=gen,
                           temperature=temperature) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            eng.step()
        wall = time.monotonic() - t0
        st = eng.stats()
        eng.shutdown()
        total = sum(len(r.output) for r in reqs)
        sp = st.get("spec") or {}
        return total / wall, sp.get("accept_rate")

    for name, mix_len in mixes:
        base_tps, _ = run(mix_len)
        self_tps, self_ar = run(mix_len, draft=(params, cfg))
        tiny_tps, tiny_ar = run(mix_len, draft=(draft_params, draft_cfg))
        rows.append({
            "metric": f"decode_spec_accept_rate_{name}",
            "value": round(self_ar or 0.0, 3),
            "unit": "accepted/proposed",
            "note": (f"greedy, k={k}, self-draft bracket (tiny random "
                     f"draft floor: {tiny_ar}); prompt {mix_len} + "
                     f"{gen} new x {slots} slots; {platform}"),
        })
        rows.append({
            "metric": f"decode_spec_tokens_per_s_{name}",
            "value": round(self_tps, 2),
            "unit": "tokens/s",
            "note": (f"greedy spec engine tokens/s at the self-draft "
                     f"bracket ({self_tps / base_tps:.2f}x plain "
                     f"{base_tps:.1f}; tiny-draft floor "
                     f"{tiny_tps:.1f} = {tiny_tps / base_tps:.2f}x); "
                     f"k={k}; {platform}"),
        })
        samp_tps, _ = run(mix_len, temperature=0.8,
                          draft=(params, cfg), sampler=True)
        rows.append({
            "metric": f"decode_spec_sampled_tokens_per_s_{name}",
            "value": round(samp_tps, 2),
            "unit": "tokens/s",
            "note": (f"temp 0.8 mix on the SAME spec-configured "
                     f"engine: spec disengages (argmax acceptance "
                     f"rule), fused device sampler carries the batch "
                     f"({samp_tps / base_tps:.2f}x the greedy plain "
                     f"path); {platform}"),
        })

    # ---- donated-buffer + device-sampler step-time delta (512 mix)
    host_tps, _ = run(512, temperature=0.8, sampler=False)
    dev_tps, _ = run(512, temperature=0.8, sampler=True)
    rows.append({
        "metric": "decode_device_sampler_step_delta",
        "value": round((1e3 * slots / host_tps)
                       - (1e3 * slots / dev_tps), 3),
        "unit": "ms/step",
        "note": (f"host-sampler minus device-sampler mean step time at "
                 f"temp 0.8 (host {1e3 * slots / host_tps:.2f} ms, "
                 f"device {1e3 * slots / dev_tps:.2f} ms; device path "
                 f"keeps logits on-device and feeds the donated token "
                 f"buffer back without a host round-trip); 512-token "
                 f"prompts x {slots} slots; {platform}"),
    })
    return rows


def trace_overhead_rows(params, cfg, quick: bool, platform: str = ""):
    """Tracing+metrics overhead on the decode STEP LOOP: the same
    steady full-batch decode measured with the observability layer
    armed (step-timeline ring + SLO metrics + trace spans, the
    defaults) vs stripped. Per-request costs (terminal histograms,
    spans) amortize over a request's tokens; the per-STEP cost is the
    ring recorder's clock reads + deque append, and the acceptance bar
    is <2% on this bench."""
    import statistics as stats

    from ray_tpu.serve.decode import DecodeEngine

    import numpy as np

    slots = 4
    steps = 100 if quick else 200
    repeats = 4 if quick else 6
    capacity = 4096
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, 16).tolist()
               for _ in range(slots)]

    def measure(**obs):
        eng = DecodeEngine(params, cfg, slots=slots, capacity=capacity,
                           prefix_pool_entries=0, **obs)
        # Slots stay occupied for the whole measurement: the loop times
        # pure decode steps, no admissions after warmup.
        reqs = [eng.submit(p, max_new_tokens=capacity - 64)
                for p in prompts]
        for _ in range(20):
            eng.step()
        samples = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(steps):
                eng.step()
            samples.append((time.perf_counter() - t0) / steps)
        for r in reqs:
            eng.cancel(r.request_id)
        eng.step()
        eng.shutdown()
        return stats.median(samples)

    t_off = measure(step_timeline=0, metrics_enabled=False,
                    trace_spans=False)
    t_on = measure()  # config defaults: ring + metrics + spans armed
    overhead = (t_on - t_off) / t_off * 100.0
    return [{
        "metric": "decode_step_overhead_traced_pct",
        "value": round(overhead, 2), "unit": "%",
        "note": (f"decode step loop traced {t_on * 1e6:.0f}us vs "
                 f"untraced {t_off * 1e6:.0f}us per step (median of "
                 f"{repeats} x {steps}-step segments, {slots} active "
                 f"slots; instrumented = step-timeline ring + SLO "
                 f"metrics + trace spans at defaults); bar <2%; "
                 f"{platform}"),
    }]


def serve_stack_row(cfg, quick: bool, platform: str = "",
                    cpu: bool = False):
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.decode import LlamaDecodeDeployment

    import numpy as np

    gen = 8 if quick else 32
    clients = 2 if quick else 8
    duration = 5 if quick else 20
    dep = serve.deployment(LlamaDecodeDeployment).options(
        max_ongoing_requests=64, max_concurrency=32,
        ray_actor_options=(
            {} if quick or cpu else {"resources": {"TPU": 1.0}}),
    ).bind(config=cfg, slots=4 if quick else 16, capacity=256,
           decode_chunk=8)
    serve.run(dep, name="llm_decode")
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if serve.status().get("llm_decode", {}).get("replicas", 0) >= 1:
            break
        time.sleep(0.5)
    handle = serve.get_deployment_handle("llm_decode")
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 16 if quick else 64).tolist()
    # Warm (retry through the replica-registration race).
    for _ in range(120):
        try:
            handle.remote({"tokens": prompt, "max_new_tokens": 2}).result(
                timeout=300)
            break
        except RuntimeError:
            time.sleep(1.0)

    stop = time.monotonic() + duration
    lat, tokens = [], [0]
    lock = threading.Lock()

    def client():
        while time.monotonic() < stop:
            t0 = time.monotonic()
            out = handle.remote({"tokens": prompt,
                                 "max_new_tokens": gen}).result(
                timeout=300)
            dt = time.monotonic() - t0
            with lock:
                lat.append(dt * 1e3)
                tokens[0] += len(out["tokens"])

    threads = [threading.Thread(target=client) for _ in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    rows = [{
        "metric": "decode_serve_stack_tokens_per_s",
        "value": round(tokens[0] / wall, 1),
        "unit": "tokens/s",
        "note": (f"{clients} closed-loop clients x {gen} new tokens/req "
                 f"through controller-routed handle, {len(lat)} reqs, "
                 f"req p50={pctl(lat, 0.5):.0f}ms "
                 f"p99={pctl(lat, 0.99):.0f}ms; nearest-rank pctl; "
                 f"{platform}"),
    }]
    # TTFT/per-token percentiles from the REPLICA-side SLO histograms
    # (serve/metrics.py), aggregated by the cluster controller — the
    # same numbers serve.status()["..."]["slo"] and /metrics report.
    # Replica flushers push every metrics_flush_interval_s; poll.
    from ray_tpu.core.runtime import get_core_worker

    agg = None
    deadline2 = time.monotonic() + 15.0
    while time.monotonic() < deadline2:
        agg = get_core_worker().controller.call("list_metrics",
                                                timeout=10.0)
        if hist_pctl_ms("llm_decode", "serve_ttft_s", 0.5,
                        aggregated=agg) is not None:
            break
        time.sleep(0.5)
    ttft_p50 = hist_pctl_ms("llm_decode", "serve_ttft_s", 0.5,
                            aggregated=agg)
    if ttft_p50 is not None:
        ttft_p99 = hist_pctl_ms("llm_decode", "serve_ttft_s", 0.99,
                                aggregated=agg)
        tok_p50 = hist_pctl_ms("llm_decode", "serve_inter_token_s", 0.5,
                               aggregated=agg)
        tok_p99 = hist_pctl_ms("llm_decode", "serve_inter_token_s",
                               0.99, aggregated=agg)
        rows.append({
            "metric": "decode_serve_stack_ttft_p50",
            "value": round(ttft_p50, 1), "unit": "ms",
            "note": (f"TTFT p99={ttft_p99:.0f}ms, per-token "
                     f"p50={tok_p50:.1f}ms p99={tok_p99:.1f}ms — from "
                     f"the controller-aggregated serve_ttft_s/"
                     f"serve_inter_token_s histograms (bucket-"
                     f"interpolated pctl, same source as serve.status "
                     f"slo + /metrics); {platform}"),
        })
    serve.shutdown()
    return rows


def sharded_rows(quick: bool, platform: str):
    """GSPMD model-parallel decode rows (ISSUE 7): a (2, 4) mesh engine
    vs the single-chip engine on the same model — (a) decode tokens/s
    (on the 1-core CPU host the 8 virtual devices time-slice, so the
    sharded row measures partitioning OVERHEAD, not speedup — a real
    slice gets the model-axis compute in parallel); (b) HBM-per-chip
    headroom: bytes of weights + KV pool resident per device, sharded
    vs single-chip — the model-size unlock, backend-independent.
    Bit-exactness of the sharded logits is asserted in
    tests/test_sharded_decode.py, not re-proven here."""
    import jax
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.decode import DecodeEngine

    if jax.device_count() < 8:
        print("sharded section skipped: needs 8 devices "
              f"(have {jax.device_count()})")
        return []
    # all sharded dims divisible by 8 so the (2, 4) mesh really shards
    cfg = llama.LlamaConfig(
        vocab_size=512, dim=128, n_layers=2 if quick else 4, n_heads=8,
        n_kv_heads=8, mlp_dim=512, max_seq_len=1024)
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    slots, capacity, T = 8, 512, 64
    gen = 16 if quick else 64
    prompts = [rng.integers(0, cfg.vocab_size, 48).tolist()
               for _ in range(slots)]

    def per_chip_bytes(tree):
        """Bytes one device holds of a (possibly sharded) pytree."""
        dev0 = jax.devices()[0]
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            for shard in leaf.addressable_shards:
                if shard.device == dev0:
                    total += shard.data.nbytes
        return total

    def run(mesh_shape):
        eng = DecodeEngine(params, cfg, slots=slots, capacity=capacity,
                           page_tokens=T, prefix_pool_entries=0,
                           mesh_shape=mesh_shape)
        warm = [eng.submit(p, max_new_tokens=2) for p in prompts[:2]]
        while not all(w.done.is_set() for w in warm):
            eng.step()
        reqs = [eng.submit(p, max_new_tokens=gen) for p in prompts]
        t0 = time.monotonic()
        while not all(r.done.is_set() for r in reqs):
            eng.step()
        wall = time.monotonic() - t0
        toks = sum(len(r.output) for r in reqs)
        return (toks / wall, per_chip_bytes(eng.params),
                per_chip_bytes({"k": eng.cache["k"],
                                "v": eng.cache["v"]}))

    single_tps, single_pb, single_kb = run(None)
    shard_tps, shard_pb, shard_kb = run((2, 4))
    return [
        {"metric": "decode_sharded_tokens_per_s",
         "value": round(shard_tps, 1), "unit": "tok/s",
         "note": (f"(2,4) batch x model mesh over 8 virtual devices vs "
                  f"{single_tps:.1f} tok/s single-chip, same model/"
                  f"capacity (slots={slots}, paged T={T}, +{gen} new); "
                  f"1-core CPU host time-slices the mesh — partitioning "
                  f"overhead row, NOT a speedup claim; logits bit-exact "
                  f"(test_sharded_decode.py); {platform}")},
        {"metric": "decode_sharded_hbm_params_per_chip",
         "value": round(shard_pb / 1e6, 3), "unit": "MB",
         "note": (f"weights resident per chip on the (2,4) mesh vs "
                  f"{single_pb / 1e6:.3f} MB single-chip "
                  f"({single_pb / max(1, shard_pb):.2f}x headroom; "
                  f"wo/w_down stay replicated for bit-exactness); "
                  f"{platform}")},
        {"metric": "decode_sharded_hbm_kv_per_chip",
         "value": round(shard_kb / 1e6, 3), "unit": "MB",
         "note": (f"paged KV pool bytes per chip on the (2,4) mesh vs "
                  f"{single_kb / 1e6:.3f} MB single-chip "
                  f"({single_kb / max(1, shard_kb):.2f}x: kv-head dim "
                  f"shards over the model axis); {platform}")},
    ]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--sections",
        default="engine,serve,shared_prefix,overload,paged,sharded,"
                "spec,trace_overhead",
        help="comma-set of row groups to (re)measure: engine, serve, "
             "shared_prefix, overload, paged, sharded, spec, "
             "trace_overhead. Only the selected groups' rows are "
             "replaced in BENCH_SERVE.json; the rest are preserved.")
    parser.add_argument(
        "--model", default=None,
        help="llama preset override (default: debug if --quick else "
             "160m)")
    parser.add_argument(
        "--cpu", action="store_true",
        help="force JAX_PLATFORMS=cpu but still write BENCH_SERVE.json "
             "(rows are annotated with the platform)")
    args = parser.parse_args()
    sections = {s.strip() for s in args.sections.split(",") if s.strip()}

    if "sharded" in sections:
        # The sharded rows span an 8-device mesh; on a CPU host that
        # means the forced virtual devices (must be set before jax
        # initializes its backend).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if args.quick or args.cpu:
        # Env var too: serve replica workers inherit it at fork, so the
        # whole quick path (driver + replicas) stays on CPU.
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import ray_tpu
    from ray_tpu.models import llama

    preset = args.model or ("debug" if args.quick else "160m")
    cfg = llama.PRESETS[preset]
    params = llama.init_params(cfg, jax.random.key(0))
    platform = jax.devices()[0].platform
    plat_note = f"{preset} model, {platform} backend"

    rows = []
    if "engine" in sections:
        rows += engine_rows(params, cfg, args.quick, plat_note)
    if "shared_prefix" in sections:
        rows += shared_prefix_rows(params, cfg, args.quick, plat_note)
    if "overload" in sections:
        rows += overload_rows(params, cfg, args.quick, plat_note)
    if "paged" in sections:
        rows += paged_rows(args.quick, f"{platform} backend")
    if "sharded" in sections:
        rows += sharded_rows(args.quick, f"{platform} backend")
    if "spec" in sections:
        rows += spec_rows(args.quick, f"{platform} backend")
    if "trace_overhead" in sections:
        rows += trace_overhead_rows(params, cfg, args.quick, plat_note)
    if "serve" in sections:
        ray_tpu.init(num_cpus=4)
        try:
            rows += serve_stack_row(cfg, args.quick, plat_note,
                                    cpu=args.cpu)
        finally:
            ray_tpu.shutdown()

    out_path = "BENCH_SERVE.json"
    doc = {"artifact": "BENCH_SERVE", "rows": []}
    if os.path.exists(out_path) and not args.quick:
        with open(out_path) as f:
            doc = json.load(f)
        # Replace exactly the rows this run re-measured.
        emitted = {r["metric"] for r in rows}
        doc["rows"] = [r for r in doc.get("rows", [])
                       if r["metric"] not in emitted]
    if args.quick:
        out_path = "/tmp/bench_decode_quick.json"
    doc.setdefault("decode_model",
                   "llama-160m, KV-cache continuous batching "
                   "(serve/decode.py), bf16")
    doc["rows"] = doc.get("rows", []) + rows
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(rows))


if __name__ == "__main__":
    main()
