import dataclasses
from ray_tpu.models import llama

d1152 = llama.LlamaConfig(vocab_size=32000, dim=1152, n_layers=24, n_heads=9,
                          n_kv_heads=9, mlp_dim=4608, max_seq_len=2048)
d1280 = llama.LlamaConfig(vocab_size=32000, dim=1280, n_layers=24, n_heads=10,
                          n_kv_heads=10, mlp_dim=5120, max_seq_len=2048)
fl = lambda c, **kw: dataclasses.replace(c, attention_impl="flash", **kw)
CONFIGS = [
    ("d1152 xla full ce512 b16", dataclasses.replace(d1152, loss_chunk=512), 16, 2048),
    ("d1152 flash full ce512 b24", fl(d1152, loss_chunk=512), 24, 2048),
    ("d1152 flash full ce512 b32", fl(d1152, loss_chunk=512), 32, 2048),
    ("d1152 flash norem ce512 b4", fl(d1152, loss_chunk=512, remat=False), 4, 2048),
    ("d1152 flash full ce512 b8 s4096",
     fl(dataclasses.replace(d1152, max_seq_len=4096), loss_chunk=512), 8, 4096),
    ("d1280 flash full ce512 b16", fl(d1280, loss_chunk=512), 16, 2048),
]
