"""Compiled-DAG pipeline microbenchmarks: channel transport + 1F1B overlap.

Two measurement families (rows land in MICROBENCH.md):

1. **Transport**: per-item latency of a 3-stage pipeline at 1 KB / 1 MB
   payloads, shm channels vs RPC pushes (reference: mutable-plasma
   channels, shared_memory_channel.py:169).
2. **Overlap (1F1B shape)**: a 4-stage pipeline whose stages do OFF-CPU
   work (sleep = device/TPU compute) on 4 MB activations. With a 1-deep
   channel the writer cannot place item k+1 while item k is still being
   processed (unacked), so inter-stage TRANSFER serializes with compute;
   ring channels (default depth 3) stream the next items into the free
   slots meanwhile. Reports wall per depth + bubble fraction vs the
   ideal (M + S - 1) x work schedule.

   Measured findings on THIS box (1 core), reported as-is in
   MICROBENCH.md: (a) per-edge buffering of "1 unacked + 1 in the
   writer's hand" means even depth 1 absorbs iid stage-time jitter
   almost fully (classic tandem-queue result); (b) the 4-stage 4 MB
   pipeline is serialization-CPU-bound at ~20 ms/item (4 stages x ~4 ms
   frame-build + driver I/O on one core), so depths 1 and 3 measure
   equal here — the ring's overlap win (serialize item k+1 during item
   k's device time) requires a core for the serializer; the
   writer-runs-ahead property itself is proven at the protocol level in
   tests/test_dag.py::test_mutable_channel_ring_overlap.

Run: ``python microbench_pipeline.py [--quick]``.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def build_pipeline(ray_tpu, n_stages: int, work_s: float = 0.0):
    from ray_tpu.dag import InputNode

    def stage_fn(_salt):
        def fn(x):
            if work_s:
                time.sleep(work_s)  # device work: off-CPU
            return x

        return fn

    stages = [ray_tpu.remote(stage_fn(s)) for s in range(n_stages)]
    with InputNode() as inp:
        dag = inp
        for s in stages:
            dag = s.bind(dag)
    return dag


def run_items(compiled, items, timeout=600.0):
    t0 = time.perf_counter()
    futs = [compiled.execute(x) for x in items]
    outs = [f.result(timeout=timeout) for f in futs]
    return time.perf_counter() - t0, outs


def transport_rows(ray_tpu, config, n_items: int):
    rows = []
    for label, payload in (("1KB", np.zeros(128, np.float64)),
                           ("1MB", np.zeros(131072, np.float64))):
        per = {}
        for mode, enabled in (("channels", True), ("rpc", False)):
            config.dag_channels_enabled = enabled
            compiled = build_pipeline(ray_tpu, 3) \
                .experimental_compile(max_in_flight=8)
            try:
                run_items(compiled, [payload] * 8)  # warm
                wall, outs = run_items(compiled, [payload] * n_items)
                assert len(outs) == n_items
                per[mode] = wall / n_items * 1e6
            finally:
                compiled.teardown()
        rows.append({
            "metric": f"dag_pipeline_3stage_{label}_us_per_item",
            "channels": round(per["channels"], 0),
            "rpc": round(per["rpc"], 0),
            "speedup": round(per["rpc"] / per["channels"], 2),
        })
        print(json.dumps(rows[-1]), flush=True)
    config.dag_channels_enabled = True
    return rows


def overlap_rows(ray_tpu, config, n_items: int):
    n_stages = 4
    work_s = 0.010
    ideal = (n_items + n_stages - 1) * work_s
    payload = np.zeros(524288, np.float64)  # 4 MB activations
    row = {"metric": "dag_1f1b_4stage_4MB_wall_s",
           "items": n_items, "stage_work_ms": work_s * 1000,
           "ideal_s": round(ideal, 2)}
    for depth in (1, 3):
        config.dag_channel_slots = depth
        compiled = build_pipeline(ray_tpu, n_stages, work_s=work_s) \
            .experimental_compile(max_in_flight=2 * depth + 4)
        try:
            run_items(compiled, [payload] * 4)  # warm
            # Best-of-3: this box has background-load phases that swamp a
            # single rep (same discipline as the MFU probes).
            wall = min(run_items(compiled, [payload] * n_items)[0]
                       for _ in range(3))
            row[f"slots{depth}_wall_s"] = round(wall, 2)
            row[f"slots{depth}_bubble_frac"] = round(1 - ideal / wall, 3)
        finally:
            compiled.teardown()
    row["speedup_ring_vs_1slot"] = round(
        row["slots1_wall_s"] / row["slots3_wall_s"], 2)
    config.dag_channel_slots = 3
    print(json.dumps(row), flush=True)
    return [row]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu.core.config import config

    ray_tpu.init(num_cpus=8)
    try:
        rows = transport_rows(ray_tpu, config, 20 if args.quick else 100)
        rows += overlap_rows(ray_tpu, config, 20 if args.quick else 60)
        print(json.dumps({"rows": rows}, indent=2))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
