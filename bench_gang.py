"""Multi-host gang bench (ISSUE 13): gang formation latency,
member-death -> reconciled MTTR, and coordinator-failover MTTR for
2/4/8-host VIRTUAL groups (one dev-box node advertising an 8x8 grid at
8 chips per host = 8 virtual hosts), all faults driven through
util/faultinject at the member beat site — never ad-hoc kills.

Rows merge into BENCH_SERVE.json preserving every other row (the PR 6
merge idiom):

* ``gang_form_s_{n}h``        — HostGroup.start(): reserve + register
  + spawn n members + elect + configure;
* ``gang_member_mttr_s_{n}h`` — SIGKILL a non-coordinator member ->
  whole-gang reconciled (fresh members, bumped epoch, old sub-slice
  released exactly once);
* ``gang_coord_mttr_s_{n}h``  — SIGKILL the COORDINATOR -> re-election
  completes under the bumped epoch.

Run: ``make bench-gang`` (CPU host; the bound being measured is
control-plane latency, so no accelerator is involved).
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="write /tmp instead of BENCH_SERVE.json")
    parser.add_argument("--sizes", default="2,4,8",
                        help="comma-separated gang sizes")
    args = parser.parse_args()

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["RAY_TPU_VIRTUAL_SLICE"] = "8x8/8"
    faults_path = f"/tmp/ray_tpu_bench_gang_{os.getpid()}.json"
    os.environ["RAY_TPU_FAULTINJECT_PATH"] = faults_path

    import ray_tpu
    from ray_tpu.core.config import config
    from ray_tpu.core.multihost import HostGroup
    from ray_tpu.util.faultinject import Faults

    config.faultinject_path = faults_path
    ray_tpu.init(num_cpus=16)

    sizes = [int(s) for s in args.sizes.split(",")]
    rows = []

    def wait_epoch(group, epoch, timeout=90.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = group.status()
            if st["epoch"] >= epoch and st["state"] == "ALIVE":
                return True
            time.sleep(0.05)
        return False

    for n in sizes:
        # ---------------------------------------------- formation
        t0 = time.monotonic()
        g = HostGroup(n, name=f"bench-form-{n}",
                      max_group_restarts=2).start()
        form_s = time.monotonic() - t0
        rows.append({
            "metric": f"gang_form_s_{n}h",
            "value": round(form_s, 3), "unit": "s",
            "note": (f"{n}-host gang: reserve sub-slice + register + "
                     f"spawn {n} members + elect coordinator + "
                     f"configure (virtual 8x8/8 slice, cpu host)")})

        # ------------------------------------- member-death MTTR
        victim = f"host-{n - 1}"  # non-coordinator
        with Faults(faults_path) as f:
            rule = f.add(f"multihost.member.bench-form-{n}.{victim}.beat",
                         "die", once_global=True,
                         rule_id=f"kill-m-{n}")
            deadline = time.monotonic() + 30.0
            while (not f.marker_fired(rule)
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            t0 = time.monotonic()
            assert wait_epoch(g, 2), g.status()
            mttr = time.monotonic() - t0
        rows.append({
            "metric": f"gang_member_mttr_s_{n}h",
            "value": round(mttr, 3), "unit": "s",
            "note": (f"SIGKILL {victim} (faultinject at its beat site) "
                     f"-> whole {n}-host gang reconciled: all members "
                     f"respawned under epoch 2, old sub-slice released "
                     f"once; beat {config.mh_member_beat_period_s}s / "
                     f"monitor {config.mh_monitor_period_s}s")})

        # ------------------------------- coordinator-failover MTTR
        with Faults(faults_path) as f:
            rule = f.add(f"multihost.member.bench-form-{n}.host-0.beat",
                         "die", once_global=True,
                         rule_id=f"kill-c-{n}")
            deadline = time.monotonic() + 30.0
            while (not f.marker_fired(rule)
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            t0 = time.monotonic()
            assert wait_epoch(g, 3), g.status()
            coord_mttr = time.monotonic() - t0
            coord = g.coordinator()
            assert coord["epoch"] == 3, coord
        rows.append({
            "metric": f"gang_coord_mttr_s_{n}h",
            "value": round(coord_mttr, 3), "unit": "s",
            "note": (f"SIGKILL the COORDINATOR (host-0) of the "
                     f"{n}-host gang -> re-election completed: fresh "
                     f"gang under epoch 3, fenced election record "
                     f"rewritten, deposed epoch rejected")})
        g.shutdown()

    ray_tpu.shutdown()

    out_path = "BENCH_SERVE.json"
    doc = {"artifact": "BENCH_SERVE", "rows": []}
    if os.path.exists(out_path) and not args.quick:
        with open(out_path) as f:
            doc = json.load(f)
        emitted = {r["metric"] for r in rows}
        doc["rows"] = [r for r in doc.get("rows", [])
                       if r["metric"] not in emitted]
    if args.quick:
        out_path = "/tmp/bench_gang_quick.json"
    doc["rows"] = doc.get("rows", []) + rows
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
