import dataclasses, time, gc
import jax, optax
from ray_tpu.models import llama
from ray_tpu.parallel import train_step as ts
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.tpu import peak_flops_per_chip

base = llama.LlamaConfig(vocab_size=32000, dim=1280, n_layers=24, n_heads=16,
                         n_kv_heads=16, mlp_dim=5120, max_seq_len=2048)
mesh = MeshSpec(fsdp=-1).build()
peak = peak_flops_per_chip()

def try_one(cfg, batch, seq=2048, steps=8):
    try:
        params = ts.init_sharded_params(lambda k: llama.init_params(cfg, k),
                                        llama.param_axes(), mesh, jax.random.key(0))
        opt = optax.adamw(3e-4)
        opt_state = ts.init_optimizer_state(opt, params)
        step = ts.build_train_step(lambda p, b: llama.loss_fn(p, b, cfg), opt, mesh)
        batch_data = ts.shard_batch({"tokens": jax.random.randint(
            jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size)}, mesh)
        params, opt_state, m = step(params, opt_state, batch_data)
        jax.block_until_ready(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, m = step(params, opt_state, batch_data)
        jax.block_until_ready(m["loss"])
        dt = (time.perf_counter() - t0) / steps
        del params, opt_state, batch_data
        gc.collect()
        tps = batch * seq / dt
        mfu = 100 * tps * llama.flops_per_token(cfg, seq) / peak
        return round(mfu, 2), round(tps)
    except Exception as e:
        gc.collect()
        return None, str(type(e).__name__)

ce = dataclasses.replace(base, loss_chunk=512)
dots = dataclasses.replace(base, loss_chunk=512, remat_policy="dots")
nore = dataclasses.replace(base, loss_chunk=512, remat=False)
one_b = dataclasses.replace(llama.PRESETS["1b"], max_seq_len=2048,
                            loss_chunk=512)
one_b_dots = dataclasses.replace(one_b, remat_policy="dots")
for desc, cfg, batch in [
    ("ce b8", ce, 8),
    ("ce b16", ce, 16),
    ("ce+dots b8", dots, 8),
    ("ce+dots b16", dots, 16),
    ("ce+noremat b8", nore, 8),
    ("ce+dots b12", dots, 12),
    ("1b ce b8", one_b, 8),
    ("1b ce+dots b8", one_b_dots, 8),
    ("1b ce b4", one_b, 4),
]:
    mfu, tps = try_one(cfg, batch)
    print(f"{desc:22s} -> MFU {mfu} ({tps})", flush=True)
