"""Control-plane MTTR bench (ISSUE 12): SIGKILL the serve controller
under live streaming load — via the util/faultinject harness, at a
named site — and measure how long the control plane takes to come back,
plus what the data plane noticed (it should notice nothing).

Rows merge into BENCH_SERVE.json preserving every other row (the PR 6
merge idiom):

* ``chaos_controller_mttr_s``       — detection (first failed probe)
  -> routing snapshots flowing again under the bumped epoch;
* ``chaos_controller_outage_s``     — SIGKILL -> recovered status;
* ``chaos_inflight_stream_failures``— streams broken by the death
  (bound: 0 — controller death is a non-event for the data plane);
* ``chaos_adopted_replicas``        — replicas adopted in place
  (same actor ids, no respawn, no cold start).

Run: ``make bench-chaos`` (CPU host; the bound being measured is
control-plane latency, so no accelerator is involved).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="write /tmp instead of BENCH_SERVE.json")
    args = parser.parse_args()

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    faults_path = f"/tmp/ray_tpu_bench_chaos_{os.getpid()}.json"
    os.environ["RAY_TPU_FAULTINJECT_PATH"] = faults_path

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import config
    from ray_tpu.serve.deployment import _Router
    from ray_tpu.util.faultinject import Faults

    config.faultinject_path = faults_path
    ray_tpu.init(num_cpus=4)

    class Streamer:
        def __call__(self, req):
            for i in range(int(req["n"])):
                time.sleep(0.03)
                yield i

    serve.run(serve.deployment(Streamer, num_replicas=2).options(
        max_concurrency=16, max_ongoing_requests=32), name="bench_app")
    handle = serve.get_deployment_handle("bench_app")
    list(handle.stream({"n": 2}))  # warm

    router = _Router.get("bench_app")
    with router._lock:
        actors0 = {r["id"]: r["handle"].actor_id.hex()
                   for r in router._replicas}
    epoch0 = router._ctrl_epoch

    results, errors = [], []

    def client():
        try:
            results.append(list(handle.stream({"n": 120})))
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.4)

    with Faults(faults_path) as faults:
        kill = faults.add("serve.controller.reconcile_tick", "die",
                          once_global=True, rule_id="bench-kill")
        while not faults.marker_fired(kill):
            time.sleep(0.02)
        t_kill = time.monotonic()
        faults.clear()

    # Detection: the first (failing) status probe reports the death and
    # triggers the restart; MTTR runs from here to snapshots flowing.
    t_detect = time.monotonic()
    while True:
        st = serve.status(timeout=5)
        if not st.get("bench_app", {}).get("degraded") \
                and len(st.get("bench_app", {}).get("replica_ids",
                                                    ())) == 2:
            break
        time.sleep(0.1)
    t_status = time.monotonic()
    while router._ctrl_epoch <= epoch0:
        time.sleep(0.02)
    t_snap = time.monotonic()

    for t in threads:
        t.join()
    ok = sum(1 for r in results if r == list(range(120)))
    with router._lock:
        actors1 = {r["id"]: r["handle"].actor_id.hex()
                   for r in router._replicas}
    adopted = sum(1 for k, v in actors0.items()
                  if actors1.get(k) == v)

    mttr = max(t_snap, t_status) - t_detect
    rows = [
        {"metric": "chaos_controller_mttr_s",
         "value": round(mttr, 3), "unit": "s",
         "note": f"detection -> snapshots+status recovered; bound "
                 f"{config.serve_mttr_bound_s:.0f}s "
                 f"(serve_mttr_bound_s); faultinject SIGKILL at "
                 f"serve.controller.reconcile_tick"},
        {"metric": "chaos_controller_outage_s",
         "value": round(max(t_snap, t_status) - t_kill, 3), "unit": "s",
         "note": "SIGKILL -> recovered (includes idle pre-detection "
                 "gap while streams drained)"},
        {"metric": "chaos_inflight_stream_failures",
         "value": len(errors), "unit": "streams",
         "note": f"{ok}/6 streams completed token-perfect across the "
                 f"controller death (bound: 0 failures)"},
        {"metric": "chaos_adopted_replicas",
         "value": adopted, "unit": "replicas",
         "note": "restarted controller adopted in place (actor ids "
                 "unchanged, no respawn) out of 2"},
    ]
    assert not errors, errors
    assert adopted >= 1, (actors0, actors1)
    assert mttr <= config.serve_mttr_bound_s, mttr

    serve.shutdown()
    ray_tpu.shutdown()

    out_path = "BENCH_SERVE.json"
    doc = {"artifact": "BENCH_SERVE", "rows": []}
    if os.path.exists(out_path) and not args.quick:
        with open(out_path) as f:
            doc = json.load(f)
        emitted = {r["metric"] for r in rows}
        doc["rows"] = [r for r in doc.get("rows", [])
                       if r["metric"] not in emitted]
    if args.quick:
        out_path = "/tmp/bench_chaos_quick.json"
    doc["rows"] = doc.get("rows", []) + rows
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
