"""Control-plane MTTR bench (ISSUE 12): SIGKILL the serve controller
under live streaming load — via the util/faultinject harness, at a
named site — and measure how long the control plane takes to come back,
plus what the data plane noticed (it should notice nothing).

Rows merge into BENCH_SERVE.json preserving every other row (the PR 6
merge idiom):

* ``chaos_controller_mttr_s``       — detection (first failed probe)
  -> routing snapshots flowing again under the bumped epoch;
* ``chaos_controller_outage_s``     — SIGKILL -> recovered status;
* ``chaos_inflight_stream_failures``— streams broken by the death
  (bound: 0 — controller death is a non-event for the data plane);
* ``chaos_adopted_replicas``        — replicas adopted in place
  (same actor ids, no respawn, no cold start).

Autopilot rows (ISSUE 18) — the closed-loop remediator driven against
the same cluster, chaos first, then a healthy soak:

* ``autopilot_mttr_s``              — gang-death signature first seen
  -> fenced ``autopilot_evict`` applied -> gang ALIVE under a bumped
  epoch (detection-to-remediated, doctor cadence compressed to 1s);
* ``autopilot_actions_taken``       — applied actions across the chaos
  phase (taint-host on an RTT outlier + reschedule-gang eviction),
  each fenced, rate-limited and audit-logged;
* ``autopilot_false_remediations``  — applied actions across live
  doctor windows on the HEALTHY cluster (bound: 0 — stale post-mortem
  signatures must fence to no-ops, never replayed mutations).

Run: ``make bench-chaos`` (CPU host; the bound being measured is
control-plane latency, so no accelerator is involved — see
BENCH_NOTES.md for what the virtual 4-host slice does and does not
prove about placement).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time


def _autopilot_bench() -> list:
    """Closed-loop remediation under chaos, then a healthy soak.

    Three phases against the live cluster (autopilot enabled only for
    the duration; restored after):

    1. taint-host — a heartbeat-rtt-outlier signature naming a LIVE
       node (by its 8-hex metric prefix, exactly as the doctor emits
       it) is damped for one window, then applied: the node lands in
       the topology taint set and is lifted again through the
       probe-gated ``untaint_host`` re-admission path.
    2. reschedule-gang — a real 2-host gang on the virtual slice; a
       gang-death signature is damped, then applied as a FENCED
       ``autopilot_evict`` group-KV write at the observed epoch; the
       group monitor consumes it through its own reconcile path and
       the gang comes back ALIVE under a bumped epoch. MTTR runs from
       the first window the signature was seen to the gang healthy.
    3. healthy soak — full live passes (doctor collect -> diagnose ->
       post-mortem -> step); applied actions must be ZERO. The soak
       deliberately still sees the eviction's own post-mortem trail:
       the fence (group gone / epoch moved on) is what keeps that
       stale evidence from becoming a mutation.
    """
    import ray_tpu  # noqa: F401  (cluster already initialised)
    from ray_tpu import doctor
    from ray_tpu.autopilot import Autopilot
    from ray_tpu.core.config import config
    from ray_tpu.core.multihost import HostGroup
    from ray_tpu.core.rpc_stubs import ControllerStub
    from ray_tpu.core.runtime import get_core_worker

    client = get_core_worker().controller
    saved = (config.autopilot_enabled, config.autopilot_dry_run)
    config.autopilot_enabled, config.autopilot_dry_run = True, False
    actions = 0
    try:
        pilot = Autopilot(client=client)

        # ---- 1. taint-host: RTT outlier -> live host demoted --------
        node_hex = next(n["node_id"]
                        for n in ControllerStub(client).list_nodes()
                        if n.get("alive"))
        rtt = {
            "signature": "heartbeat-rtt-outlier", "severity": "warning",
            "source": f"node:{node_hex[:8]}",
            "summary": "bench: node RTT p99 far off the fleet median",
            "evidence": {"p99_s": 0.9, "fleet_median_s": 0.01},
            "remediation": doctor._remediation(
                "taint-host", node_hex[:8],
                ("p99_s", "fleet_median_s")),
        }
        assert pilot.step([rtt]) == []  # window 1: hysteresis damps
        recs = pilot.step([rtt])        # window 2: acts
        assert [r["outcome"] for r in recs] == ["applied"], recs
        assert node_hex in ControllerStub(client).taint_state()
        actions += 1
        # Probe-gated re-admission: the host is healthy, so the probe
        # passes and the taint lifts early (instead of waiting out the
        # TTL) — keeps the soak below on a clean topology.
        res = ControllerStub(client).untaint_host(node_hex, probe=True)
        assert res["untainted"], res

        # ---- 2. reschedule-gang: fenced eviction, epoch bump --------
        g = HostGroup(2, name="ap-bench", max_group_restarts=2).start()
        try:
            gid = g.group_id
            death = {
                "signature": "gang-death", "severity": "critical",
                "source": f"group:{gid}",
                "summary": "bench: member host-1 repeatedly dying",
                "evidence": {"first_dying": "host-1",
                             "dead": ["host-1"], "old_epoch": 1,
                             "surviving_epoch": 1, "injected": True,
                             "stage": None},
                "remediation": doctor._remediation(
                    "reschedule-gang", gid,
                    ("first_dying", "dead", "old_epoch",
                     "surviving_epoch", "injected", "stage")),
            }
            t_detect = time.monotonic()
            assert pilot.step([death]) == []  # window 1: damped
            time.sleep(1.0)                   # compressed doctor cadence
            recs = pilot.step([death])        # window 2: fenced evict
            assert [r["outcome"] for r in recs] == ["applied"], recs
            deadline = time.monotonic() + 60.0
            while not (g.status()["epoch"] >= 2
                       and g.status()["state"] == "ALIVE"):
                assert time.monotonic() < deadline, g.status()
                time.sleep(0.05)
            mttr = time.monotonic() - t_detect
            actions += 1
        finally:
            g.shutdown()

        # ---- 3. healthy soak: zero false remediations ---------------
        false_rem = 0
        for _ in range(3):
            false_rem += sum(1 for r in pilot.run_once(interval_s=0.5)
                             if r["outcome"] == "applied")
    finally:
        config.autopilot_enabled, config.autopilot_dry_run = saved

    assert mttr <= 30.0, mttr
    assert false_rem == 0, pilot.status()["audit"]
    return [
        {"metric": "autopilot_mttr_s",
         "value": round(mttr, 3), "unit": "s",
         "note": "gang-death signature first seen -> fenced "
                 "autopilot_evict at the observed epoch -> monitor "
                 "reconciled the gang ALIVE under a bumped epoch; "
                 "doctor cadence compressed to 1s windows"},
        {"metric": "autopilot_actions_taken",
         "value": actions, "unit": "actions",
         "note": "taint-host (RTT outlier -> live node demoted, then "
                 "probe-gated re-admission) + reschedule-gang (fenced "
                 "eviction); every action audited (flightrec "
                 "autopilot.action + controller-KV record)"},
        {"metric": "autopilot_false_remediations",
         "value": false_rem, "unit": "actions",
         "note": "applied actions across 3 live doctor windows on the "
                 "healthy cluster (bound: 0 — the eviction's own "
                 "post-mortem trail fences to no-ops: group gone / "
                 "epoch moved on)"},
    ]


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="write /tmp instead of BENCH_SERVE.json")
    args = parser.parse_args()

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    faults_path = f"/tmp/ray_tpu_bench_chaos_{os.getpid()}.json"
    os.environ["RAY_TPU_FAULTINJECT_PATH"] = faults_path
    # The autopilot phase needs a multi-host gang: advertise a virtual
    # 4-host slice (the test_multihost_group cluster shape) and a
    # flight-recorder dir so autopilot audits flush durably.
    os.environ.setdefault("RAY_TPU_VIRTUAL_SLICE", "4x4/4")
    flightrec_dir = f"/tmp/ray_tpu_bench_chaos_fr_{os.getpid()}"
    os.environ.setdefault("RAY_TPU_FLIGHTREC_DIR", flightrec_dir)

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import config
    from ray_tpu.serve.deployment import _Router
    from ray_tpu.util.faultinject import Faults

    config.faultinject_path = faults_path
    config.flightrec_dir = os.environ["RAY_TPU_FLIGHTREC_DIR"]
    ray_tpu.init(num_cpus=8)

    class Streamer:
        def __call__(self, req):
            for i in range(int(req["n"])):
                time.sleep(0.03)
                yield i

    serve.run(serve.deployment(Streamer, num_replicas=2).options(
        max_concurrency=16, max_ongoing_requests=32), name="bench_app")
    handle = serve.get_deployment_handle("bench_app")
    list(handle.stream({"n": 2}))  # warm

    router = _Router.get("bench_app")
    with router._lock:
        actors0 = {r["id"]: r["handle"].actor_id.hex()
                   for r in router._replicas}
    epoch0 = router._ctrl_epoch

    results, errors = [], []

    def client():
        try:
            results.append(list(handle.stream({"n": 120})))
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.4)

    with Faults(faults_path) as faults:
        kill = faults.add("serve.controller.reconcile_tick", "die",
                          once_global=True, rule_id="bench-kill")
        while not faults.marker_fired(kill):
            time.sleep(0.02)
        t_kill = time.monotonic()
        faults.clear()

    # Detection: the first (failing) status probe reports the death and
    # triggers the restart; MTTR runs from here to snapshots flowing.
    t_detect = time.monotonic()
    while True:
        st = serve.status(timeout=5)
        if not st.get("bench_app", {}).get("degraded") \
                and len(st.get("bench_app", {}).get("replica_ids",
                                                    ())) == 2:
            break
        time.sleep(0.1)
    t_status = time.monotonic()
    while router._ctrl_epoch <= epoch0:
        time.sleep(0.02)
    t_snap = time.monotonic()

    for t in threads:
        t.join()
    ok = sum(1 for r in results if r == list(range(120)))
    with router._lock:
        actors1 = {r["id"]: r["handle"].actor_id.hex()
                   for r in router._replicas}
    adopted = sum(1 for k, v in actors0.items()
                  if actors1.get(k) == v)

    mttr = max(t_snap, t_status) - t_detect
    rows = [
        {"metric": "chaos_controller_mttr_s",
         "value": round(mttr, 3), "unit": "s",
         "note": f"detection -> snapshots+status recovered; bound "
                 f"{config.serve_mttr_bound_s:.0f}s "
                 f"(serve_mttr_bound_s); faultinject SIGKILL at "
                 f"serve.controller.reconcile_tick"},
        {"metric": "chaos_controller_outage_s",
         "value": round(max(t_snap, t_status) - t_kill, 3), "unit": "s",
         "note": "SIGKILL -> recovered (includes idle pre-detection "
                 "gap while streams drained)"},
        {"metric": "chaos_inflight_stream_failures",
         "value": len(errors), "unit": "streams",
         "note": f"{ok}/6 streams completed token-perfect across the "
                 f"controller death (bound: 0 failures)"},
        {"metric": "chaos_adopted_replicas",
         "value": adopted, "unit": "replicas",
         "note": "restarted controller adopted in place (actor ids "
                 "unchanged, no respawn) out of 2"},
    ]
    assert not errors, errors
    assert adopted >= 1, (actors0, actors1)
    assert mttr <= config.serve_mttr_bound_s, mttr

    rows += _autopilot_bench()

    serve.shutdown()
    ray_tpu.shutdown()

    out_path = "BENCH_SERVE.json"
    doc = {"artifact": "BENCH_SERVE", "rows": []}
    if os.path.exists(out_path) and not args.quick:
        with open(out_path) as f:
            doc = json.load(f)
        emitted = {r["metric"] for r in rows}
        doc["rows"] = [r for r in doc.get("rows", [])
                       if r["metric"] not in emitted]
    if args.quick:
        out_path = "/tmp/bench_chaos_quick.json"
    doc["rows"] = doc.get("rows", []) + rows
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
