"""Serving benchmark: jitted-Llama replica behind bucketed batching.

North-star artifact named by BASELINE.json ("Serve: Llama jitted inference
with autoscaled TPU replicas"): measures, on the real chip,

  1. handle-path throughput (requests/s, tokens/s) under closed-loop
     concurrent load through the pow-2 router + bucketed batch queue;
  2. request latency p50/p99 for the same load;
  3. HTTP-path latency through a per-node ProxyActor (the serve data
     plane — reference: serve/_private/proxy.py);
  4. autoscale-up-under-load: time for the controller to add replicas
     once ongoing-requests exceed the target (CPU replicas — one chip
     can't host two TPU replicas; the mechanism is identical,
     autoscaling_policy.py:12).

Writes BENCH_SERVE.json. Run with no env overrides so the replica sees
the attached TPU: ``python bench_serve.py [--quick]``.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time


def pctl(xs, p):
    """Nearest-rank percentile (1-indexed rank ceil(p*n)) — the old
    ``int(len(xs) * p)`` index was biased one rank high at p50 for
    even-sized samples (same fix as bench_decode.py::pctl)."""
    import math

    xs = sorted(xs)
    return xs[max(0, min(len(xs) - 1, math.ceil(p * len(xs)) - 1))]


def http_hist_pctl_ms(deployment: str, p: float, timeout_s: float = 15.0):
    """HTTP latency percentile (ms) from the PROXY's
    ``serve_http_request_s`` histogram, aggregated by the cluster
    controller — the bench reads the production instrument (same
    source as /metrics and the dashboard serve panel) instead of its
    own client-side list. Bucket-interpolated; polls for the proxy's
    first metrics flush. None when it never lands."""
    import time as _t

    from ray_tpu.core.runtime import get_core_worker
    from ray_tpu.util.metrics import histogram_quantile, merge_histograms

    deadline = _t.monotonic() + timeout_s
    while _t.monotonic() < deadline:
        agg = get_core_worker().controller.call("list_metrics",
                                                timeout=10.0)
        entry = merge_histograms(agg, "serve_http_request_s").get(
            (("deployment", deployment),))
        if entry is not None and entry["count"]:
            return histogram_quantile(entry, p) * 1e3
        _t.sleep(0.5)
    return None


SEQ_LEN = 128
# Two buckets: small for latency at low load, large for throughput under
# saturation. Probed on-chip: bucket 64 runs at ~109 ms/batch (588 seq/s)
# vs 61 ms at bucket 8 — a ~60 ms tunnel/dispatch floor dominates small
# batches, so saturated traffic wants the big bucket.
BUCKETS = [8, 64]


def llama_deployment(serve, cpu: bool = False, model: str = "160m"):
    @serve.deployment(max_ongoing_requests=128,
                      ray_actor_options=(
                          {} if cpu else {"resources": {"TPU": 1.0}}))
    class LlamaServer:
        def __init__(self):
            import jax
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models import llama

            self.cfg = llama.PRESETS[model]
            self.params = llama.init_params(self.cfg, jax.random.key(0))

            # The serving shape: score the prompt, return the NEXT TOKEN
            # per sequence. argmax happens on device — fetching the full
            # logit cube (batch x seq x vocab ~ 131 MB at bucket 8) would
            # make every batch host-transfer-bound.
            def step(p, t):
                logits = llama.forward(p, t, self.cfg)
                return jnp.argmax(logits[:, -1, :], axis=-1)

            self.fwd = jax.jit(step)
            # Compile every bucket up front (reference: compilation-cache
            # warmup on replica start — SURVEY §7 hard part 5).
            for b in BUCKETS:
                toks = np.zeros((b, SEQ_LEN), dtype=np.int32)
                np.asarray(self.fwd(self.params, toks))

        @serve.batch(max_batch_size=BUCKETS[-1], batch_wait_timeout_s=0.01,
                     pad_to_buckets=BUCKETS)
        def predict(self, token_lists):
            import numpy as np

            toks = np.asarray(token_lists, dtype=np.int32)
            next_tokens = np.asarray(self.fwd(self.params, toks))  # fetch
            return [int(t) for t in next_tokens]

        def __call__(self, token_list):
            return self.predict(token_list)

    return LlamaServer


def closed_loop(handle, seq, n_clients: int, duration_s: float):
    """n_clients threads, each fire-wait-repeat; returns latencies (s)."""
    lats = []
    lock = threading.Lock()
    stop = time.monotonic() + duration_s

    def client():
        mine = []
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            handle.remote(seq).result(timeout=120)
            mine.append(time.perf_counter() - t0)
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    return lats, wall


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--cpu", action="store_true",
        help="run the prefill-serving rows on the CPU backend (replicas "
             "lose the TPU resource requirement; rows are annotated)")
    ap.add_argument(
        "--model", default="160m",
        help="llama preset for the serving rows (the 160m default needs "
             "the rig; CPU re-measures use debug)")
    args = ap.parse_args()
    duration = 10.0 if args.quick else 30.0
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init()
    rows = []

    # ---- 1+2: handle-path throughput + latency on the TPU replica
    LlamaServer = llama_deployment(serve, cpu=args.cpu,
                                   model=args.model)
    handle = serve.run(LlamaServer.bind(), name="llama",
                       ready_timeout_s=600.0)
    seq = list(range(SEQ_LEN))
    # Warm the full path (router snapshot, batch queue, jit cache).
    for _ in range(4):
        handle.remote(seq).result(timeout=600)

    lats, wall = closed_loop(handle, seq, n_clients=64, duration_s=duration)
    n = len(lats)
    rows.append({
        "metric": "serve_throughput_requests_per_s",
        "value": round(n / wall, 1), "unit": "req/s",
        "note": f"64 closed-loop clients, {duration:.0f}s, batch buckets "
                f"{BUCKETS}, seq {SEQ_LEN}, {args.model} jitted Llama "
                f"fwd",
    })
    rows.append({
        "metric": "serve_throughput_tokens_per_s",
        "value": round(n * SEQ_LEN / wall, 0), "unit": "tokens/s",
        "note": "prefill tokens scored per second (requests x seq_len)",
    })
    rows.append({
        "metric": "serve_latency_p50",
        "value": round(pctl(lats, 0.5) * 1000, 1), "unit": "ms",
        "note": f"p99={pctl(lats, 0.99) * 1000:.1f}ms, "
                f"mean={statistics.mean(lats) * 1000:.1f}ms over {n} reqs",
    })

    # ---- 3: HTTP path through a per-node ProxyActor
    host, port = serve.start_http()
    import urllib.request

    http_lats = []
    for _ in range(20 if args.quick else 100):
        req = urllib.request.Request(
            f"http://{host}:{port}/llama", data=json.dumps(seq).encode(),
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=120) as resp:
            resp.read()
        http_lats.append(time.perf_counter() - t0)
    # Proxy-side histogram (serve/metrics.py serve_http_request_s) is
    # the source of record; the client-side list is kept only as the
    # cross-check in the note (client ms include connection setup).
    h_p50 = http_hist_pctl_ms("llama", 0.5)
    h_p99 = http_hist_pctl_ms("llama", 0.99, timeout_s=1.0)
    if h_p50 is not None:
        rows.append({
            "metric": "serve_http_latency_p50",
            "value": round(h_p50, 1), "unit": "ms",
            "note": (f"p99={h_p99:.1f}ms from the proxy's "
                     f"serve_http_request_s histogram (bucket-"
                     f"interpolated pctl; same source as /metrics); "
                     f"client-side cross-check p50="
                     f"{pctl(http_lats, 0.5) * 1000:.1f}ms via per-node "
                     f"ProxyActor (single-threaded client)"),
        })
    else:
        rows.append({
            "metric": "serve_http_latency_p50",
            "value": round(pctl(http_lats, 0.5) * 1000, 1), "unit": "ms",
            "note": f"p99={pctl(http_lats, 0.99) * 1000:.1f}ms via "
                    f"per-node ProxyActor (single-threaded client; "
                    f"proxy histogram never flushed — fallback)",
        })
    serve.delete("llama")

    # ---- 4: autoscale-up-under-load (CPU replicas; one chip = one TPU
    # replica, so the scaling mechanism is shown on the CPU pool)
    @serve.deployment(autoscaling_config=serve.AutoscalingConfig(
        min_replicas=1, max_replicas=4, target_ongoing_requests=2,
        upscale_delay_s=0.2, downscale_delay_s=60.0))
    class Slow:
        def __call__(self, x):
            time.sleep(0.25)
            return x

    s_handle = serve.run(Slow.bind(), name="scaler")
    s_handle.remote(0).result(timeout=60)
    t0 = time.monotonic()
    stop = t0 + (15.0 if args.quick else 30.0)
    scale_times = {}

    def pound():
        while time.monotonic() < stop:
            try:
                s_handle.remote(1).result(timeout=60)
            except Exception:
                pass

    threads = [threading.Thread(target=pound) for _ in range(12)]
    for t in threads:
        t.start()
    while time.monotonic() < stop:
        n_rep = serve.status()["scaler"]["replicas"]
        if n_rep not in scale_times:
            scale_times[n_rep] = time.monotonic() - t0
        if n_rep >= 4:
            break
        time.sleep(0.1)
    for t in threads:
        t.join()
    peak = max(scale_times)
    rows.append({
        "metric": "serve_autoscale_up",
        "value": (round(scale_times[2], 1) if 2 in scale_times else None),
        "unit": "s",
        "note": f"time to 2nd replica under 12-client load; reached "
                f"{peak} replicas ({ {k: round(v, 1) for k, v in sorted(scale_times.items())} }); "
                f"CPU replicas — single chip hosts one TPU replica",
    })
    serve.shutdown()

    if args.cpu:
        for r in rows:
            r["note"] += (f"; {args.model} model, cpu backend "
                          f"(nearest-rank pctl)")
    out = {
        "artifact": "BENCH_SERVE",
        "model": f"llama-{args.model} prefill, seq 128, bf32 defaults",
        "data_plane": "per-node ProxyActor (serve/proxy.py)",
        "device_probe": {
            "note": "raw jitted step on this chip (no serving stack): "
                    "bucket 8 = 61 ms, bucket 32 = 106 ms, bucket 64 = "
                    "109 ms/batch (588 seq/s, 75k tok/s). The closed-loop "
                    "gap vs serve_throughput is client+router CPU on the "
                    "1-core host, not the data plane.",
            "bucket64_seq_per_s": 588,
        },
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_SERVE.json")
    # Merge-preserve: replace exactly the rows this run re-measured —
    # clobbering bench_decode.py's decode/paged rows (as the pre-fix
    # version did) silently erased half the artifact.
    if os.path.exists(path):
        with open(path) as f:
            old = json.load(f)
        emitted = {r["metric"] for r in rows}
        out["rows"] = [r for r in old.get("rows", [])
                       if r["metric"] not in emitted] + rows
        for key, val in old.items():
            out.setdefault(key, val)
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
