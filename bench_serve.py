"""Serving benchmark: jitted-Llama replica behind bucketed batching.

North-star artifact named by BASELINE.json ("Serve: Llama jitted inference
with autoscaled TPU replicas"): measures, on the real chip,

  1. handle-path throughput (requests/s, tokens/s) under closed-loop
     concurrent load through the pow-2 router + bucketed batch queue;
  2. request latency p50/p99 for the same load;
  3. HTTP-path latency through a per-node ProxyActor (the serve data
     plane — reference: serve/_private/proxy.py);
  4. autoscale-up-under-load: time for the controller to add replicas
     once ongoing-requests exceed the target (CPU replicas — one chip
     can't host two TPU replicas; the mechanism is identical,
     autoscaling_policy.py:12).

Writes BENCH_SERVE.json. Run with no env overrides so the replica sees
the attached TPU: ``python bench_serve.py [--quick]``.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time


def pctl(xs, p):
    """Nearest-rank percentile (1-indexed rank ceil(p*n)) — the old
    ``int(len(xs) * p)`` index was biased one rank high at p50 for
    even-sized samples (same fix as bench_decode.py::pctl)."""
    import math

    xs = sorted(xs)
    return xs[max(0, min(len(xs) - 1, math.ceil(p * len(xs)) - 1))]


def http_hist_pctl_ms(deployment: str, p: float, timeout_s: float = 15.0):
    """HTTP latency percentile (ms) from the PROXY's
    ``serve_http_request_s`` histogram, aggregated by the cluster
    controller — the bench reads the production instrument (same
    source as /metrics and the dashboard serve panel) instead of its
    own client-side list. Bucket-interpolated; polls for the proxy's
    first metrics flush. None when it never lands."""
    import time as _t

    from ray_tpu.core.runtime import get_core_worker
    from ray_tpu.util.metrics import histogram_quantile, merge_histograms

    deadline = _t.monotonic() + timeout_s
    while _t.monotonic() < deadline:
        agg = get_core_worker().controller.call("list_metrics",
                                                timeout=10.0)
        entry = merge_histograms(agg, "serve_http_request_s").get(
            (("deployment", deployment),))
        if entry is not None and entry["count"]:
            return histogram_quantile(entry, p) * 1e3
        _t.sleep(0.5)
    return None


SEQ_LEN = 128
# Two buckets: small for latency at low load, large for throughput under
# saturation. Probed on-chip: bucket 64 runs at ~109 ms/batch (588 seq/s)
# vs 61 ms at bucket 8 — a ~60 ms tunnel/dispatch floor dominates small
# batches, so saturated traffic wants the big bucket.
BUCKETS = [8, 64]


def llama_deployment(serve, cpu: bool = False, model: str = "160m"):
    @serve.deployment(max_ongoing_requests=128,
                      ray_actor_options=(
                          {} if cpu else {"resources": {"TPU": 1.0}}))
    class LlamaServer:
        def __init__(self):
            import jax
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models import llama

            self.cfg = llama.PRESETS[model]
            self.params = llama.init_params(self.cfg, jax.random.key(0))

            # The serving shape: score the prompt, return the NEXT TOKEN
            # per sequence. argmax happens on device — fetching the full
            # logit cube (batch x seq x vocab ~ 131 MB at bucket 8) would
            # make every batch host-transfer-bound.
            def step(p, t):
                logits = llama.forward(p, t, self.cfg)
                return jnp.argmax(logits[:, -1, :], axis=-1)

            self.fwd = jax.jit(step)
            # Compile every bucket up front (reference: compilation-cache
            # warmup on replica start — SURVEY §7 hard part 5).
            for b in BUCKETS:
                toks = np.zeros((b, SEQ_LEN), dtype=np.int32)
                np.asarray(self.fwd(self.params, toks))

        @serve.batch(max_batch_size=BUCKETS[-1], batch_wait_timeout_s=0.01,
                     pad_to_buckets=BUCKETS)
        def predict(self, token_lists):
            import numpy as np

            toks = np.asarray(token_lists, dtype=np.int32)
            next_tokens = np.asarray(self.fwd(self.params, toks))  # fetch
            return [int(t) for t in next_tokens]

        def __call__(self, token_list):
            return self.predict(token_list)

    return LlamaServer


def closed_loop(handle, seq, n_clients: int, duration_s: float):
    """n_clients threads, each fire-wait-repeat; returns latencies (s)."""
    lats = []
    lock = threading.Lock()
    stop = time.monotonic() + duration_s

    def client():
        mine = []
        while time.monotonic() < stop:
            t0 = time.perf_counter()
            handle.remote(seq).result(timeout=120)
            mine.append(time.perf_counter() - t0)
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    return lats, wall


def _stream_lats(handle, prompts, n_reqs: int, max_new: int):
    """Sequential streamed requests over a mixed-length prompt cycle:
    client-side TTFT (submit -> first item) and inter-token gaps —
    the same stopwatch for the colocated and disaggregated paths, so
    the comparison is methodology-clean."""
    ttfts, gaps = [], []
    for i in range(n_reqs):
        prompt = prompts[i % len(prompts)]
        t0 = time.perf_counter()
        first = last = None
        n_items = 0
        for _tok in handle.stream({"tokens": prompt, "stream": True,
                                   "max_new_tokens": max_new}):
            last = time.perf_counter()
            if first is None:
                first = last
                ttfts.append(first - t0)
            n_items += 1
        # Per-request inter-token = (finish - first) / (tokens - 1):
        # raw item-to-item gaps are bursty under chunked emission (the
        # engine's own serve_inter_token_s doctrine, metrics.py).
        if n_items > 1:
            gaps.append((last - first) / (n_items - 1))
    return ttfts, gaps


def bench_disagg(args, serve) -> list:
    """Disaggregated prefill/decode rows (ROADMAP #3): mixed-length
    TTFT/inter-token p99 vs the colocated fleet, the handoff
    descriptor's wire size and publish->adopt latency from the
    production histograms, and the zero-leak soak under prefill-replica
    churn. CPU-host rows measure the MECHANISM (splice overhead,
    descriptor size, leak accounting); speedup claims wait for the rig
    (BENCH_NOTES.md)."""
    import ray_tpu
    from ray_tpu.serve.decode import LlamaDecodeDeployment
    from ray_tpu.serve.deployment import _Router
    from ray_tpu.serve.handoff import HANDOFF_DESC_BYTE_BUDGET

    rows = []
    n_reqs = 9 if args.quick else 30
    max_new = 16
    prompts = [list(range(1, 1 + n)) for n in (16, 96, 160)]
    kw = dict(preset="debug", slots=4, capacity=256, kv_page_tokens=16,
              prefill_chunk_tokens=64, prefix_pool_entries=0)

    # num_cpus=0: four CPU-host replicas must co-schedule even on a
    # 1-core box (the node's default CPU resource is os.cpu_count();
    # replicas defaulting to 1 CPU each would otherwise churn through
    # spawn/kill cycles fighting for the single slot).
    opts = dict(max_ongoing_requests=8,
                ray_actor_options={"num_cpus": 0})
    serve.run(serve.deployment(
        LlamaDecodeDeployment, role="decode",
        **opts).bind(**kw), name="dz-decode")
    serve.run(serve.deployment(
        LlamaDecodeDeployment, role="prefill",
        decode_deployment="dz-decode", num_replicas=2,
        **opts).bind(**kw), name="dz-prefill")
    serve.run(serve.deployment(
        LlamaDecodeDeployment, **opts).bind(**kw), name="dz-coloc")
    disagg = serve.get_deployment_handle("dz-prefill")
    coloc = serve.get_deployment_handle("dz-coloc")
    for h in (disagg, coloc):  # compile + snapshot warmup, unmeasured
        for p in prompts:
            h.remote({"tokens": p, "max_new_tokens": 2}).result(
                timeout=600)
        # Warm the STREAMED splice too (stream_adopted is a different
        # replica method than decode_adopted): without this the first
        # measured stream pays one-time costs and p99 reports setup,
        # not steady state.
        _stream_lats(h, prompts, len(prompts), max_new)

    c_ttft, _ = _stream_lats(coloc, prompts, n_reqs, max_new)
    d_ttft, _ = _stream_lats(disagg, prompts, n_reqs, max_new)
    mix = "/".join(str(len(p)) for p in prompts)
    rows.append({
        "metric": "disagg_ttft_p99",
        "value": round(pctl(d_ttft, 0.99) * 1000, 1), "unit": "ms",
        "note": f"streamed submit->first-token over prompt mix {mix} "
                f"({n_reqs} reqs); colocated fleet p99="
                f"{pctl(c_ttft, 0.99) * 1000:.1f}ms p50="
                f"{pctl(c_ttft, 0.5) * 1000:.1f}ms, disagg p50="
                f"{pctl(d_ttft, 0.5) * 1000:.1f}ms — disagg TTFT "
                f"carries the KV-page handoff (publish + object-plane "
                f"fetch + adopt scatter)",
    })
    # Inter-token from the ENGINE's per-request histogram (the serve
    # stream path delivers items in bursts, so a client stopwatch can't
    # see decode cadence): disagg requests decode on dz-decode, the
    # baseline on dz-coloc.
    deadline = time.monotonic() + 60
    d_it = c_it = {}
    while time.monotonic() < deadline:
        st = serve.status()
        d_it = st.get("dz-decode", {}).get("slo", {}).get(
            "inter_token_s", {})
        c_it = st.get("dz-coloc", {}).get("slo", {}).get(
            "inter_token_s", {})
        if (d_it.get("count", 0) >= n_reqs
                and c_it.get("count", 0) >= n_reqs):
            break  # the measured traffic has flushed, not just warmup
        time.sleep(0.5)
    rows.append({
        "metric": "disagg_inter_token_p99",
        "value": round((d_it.get("p99") or 0) * 1000, 2), "unit": "ms",
        "note": f"engine-side serve_inter_token_s p99 on the decode "
                f"fleet (count={d_it.get('count')}, p50="
                f"{(d_it.get('p50') or 0) * 1000:.2f}ms); colocated "
                f"fleet p99={(c_it.get('p99') or 0) * 1000:.2f}ms p50="
                f"{(c_it.get('p50') or 0) * 1000:.2f}ms — decode steps "
                f"are the same program either way, so the gap measures "
                f"the decode fleet's isolation from prefill "
                f"interference",
    })

    # Handoff wire accounting from the production instruments (same
    # source as /metrics): descriptor bytes must stay RPC-header-sized.
    deadline = time.monotonic() + 60
    slo = {}
    while time.monotonic() < deadline:
        slo = serve.status().get("dz-prefill", {}).get("slo", {})
        if slo.get("handoff_bytes", {}).get("count") \
                and slo.get("handoff_latency_s", {}).get("count"):
            break
        time.sleep(0.5)
    bytes_p99 = slo.get("handoff_bytes", {}).get("p99")
    assert bytes_p99 is not None and bytes_p99 <= HANDOFF_DESC_BYTE_BUDGET, \
        f"handoff descriptor p99 {bytes_p99} over " \
        f"{HANDOFF_DESC_BYTE_BUDGET}B budget"
    rows.append({
        "metric": "disagg_handoff_desc_bytes_p99",
        "value": round(bytes_p99, 0), "unit": "bytes",
        "note": f"pickled descriptor (refs + block geometry, never KV "
                f"payload) from serve_handoff_bytes; budget "
                f"{HANDOFF_DESC_BYTE_BUDGET}B — page payloads ride the "
                f"object plane by reference",
    })
    lat = slo.get("handoff_latency_s", {})
    rows.append({
        "metric": "disagg_handoff_latency_p50",
        "value": round((lat.get("p50") or 0) * 1000, 1), "unit": "ms",
        "note": f"publish->adopt-ack from serve_handoff_latency_s "
                f"(p99={(lat.get('p99') or 0) * 1000:.1f}ms, "
                f"count={lat.get('count')}): the window pages live as "
                f"host blobs between the fleets",
    })

    # Zero-leak soak under replica churn: SIGKILL one of two prefill
    # replicas mid-traffic, keep requesting, then audit every pool.
    router = _Router.get("dz-prefill")
    with router._lock:
        victim = router._replicas[0]["handle"]
    ray_tpu.kill(victim, no_restart=True)
    served = 0
    deadline = time.monotonic() + 120
    while served < (4 if args.quick else 12) \
            and time.monotonic() < deadline:
        try:
            disagg.remote({"tokens": prompts[served % len(prompts)],
                           "max_new_tokens": 8}).result(timeout=60)
            served += 1
        except Exception:
            time.sleep(0.5)  # mid-respawn; the router heals
    leaked = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        leaked = 0
        for name in ("dz-prefill", "dz-decode", "dz-coloc"):
            r = _Router.get(name)
            with r._lock:
                handles = [rep["handle"] for rep in r._replicas]
            for h in handles:
                try:
                    s = ray_tpu.get(h.stats.remote(), timeout=10)
                except Exception:
                    continue  # dead/respawning replica holds no pages
                leaked += int(s.get("pages_in_use", 0) or 0)
                leaked += int(s.get("handoffs_live", 0) or 0)
        if leaked == 0:
            break
        time.sleep(1.0)
    rows.append({
        "metric": "disagg_pages_leaked",
        "value": leaked, "unit": "pages+leases",
        "note": f"pages_in_use + live handoff leases across all three "
                f"fleets after {served} requests with a prefill-replica "
                f"SIGKILL mid-run (killed replica's refs die with the "
                f"owner; survivors' leases adopt-ack or abort) — must "
                f"be 0",
    })
    for name in ("dz-prefill", "dz-decode", "dz-coloc"):
        serve.delete(name)
    for r in rows:  # this section runs the debug preset, not args.model
        r["note"] += "; debug model, cpu backend (nearest-rank pctl)"
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument(
        "--cpu", action="store_true",
        help="run the prefill-serving rows on the CPU backend (replicas "
             "lose the TPU resource requirement; rows are annotated)")
    ap.add_argument(
        "--model", default="160m",
        help="llama preset for the serving rows (the 160m default needs "
             "the rig; CPU re-measures use debug)")
    ap.add_argument(
        "--sections", default="serve,autoscale",
        help="comma list of sections to run: serve (throughput/latency/"
             "http), autoscale, disagg (prefill/decode handoff rows)")
    args = ap.parse_args()
    sections = {s.strip() for s in args.sections.split(",") if s.strip()}
    duration = 10.0 if args.quick else 30.0
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init()
    rows = []

    if "disagg" in sections:
        rows += bench_disagg(args, serve)
    if "serve" in sections:
        rows += bench_serve_path(args, serve, duration)
    if "autoscale" in sections:
        rows += bench_autoscale(args, serve)
    serve.shutdown()
    _write(rows, args)


def bench_serve_path(args, serve, duration) -> list:
    rows = []
    # ---- 1+2: handle-path throughput + latency on the TPU replica
    LlamaServer = llama_deployment(serve, cpu=args.cpu,
                                   model=args.model)
    handle = serve.run(LlamaServer.bind(), name="llama",
                       ready_timeout_s=600.0)
    seq = list(range(SEQ_LEN))
    # Warm the full path (router snapshot, batch queue, jit cache).
    for _ in range(4):
        handle.remote(seq).result(timeout=600)

    lats, wall = closed_loop(handle, seq, n_clients=64, duration_s=duration)
    n = len(lats)
    rows.append({
        "metric": "serve_throughput_requests_per_s",
        "value": round(n / wall, 1), "unit": "req/s",
        "note": f"64 closed-loop clients, {duration:.0f}s, batch buckets "
                f"{BUCKETS}, seq {SEQ_LEN}, {args.model} jitted Llama "
                f"fwd",
    })
    rows.append({
        "metric": "serve_throughput_tokens_per_s",
        "value": round(n * SEQ_LEN / wall, 0), "unit": "tokens/s",
        "note": "prefill tokens scored per second (requests x seq_len)",
    })
    rows.append({
        "metric": "serve_latency_p50",
        "value": round(pctl(lats, 0.5) * 1000, 1), "unit": "ms",
        "note": f"p99={pctl(lats, 0.99) * 1000:.1f}ms, "
                f"mean={statistics.mean(lats) * 1000:.1f}ms over {n} reqs",
    })

    # ---- 3: HTTP path through a per-node ProxyActor
    host, port = serve.start_http()
    import urllib.request

    http_lats = []
    for _ in range(20 if args.quick else 100):
        req = urllib.request.Request(
            f"http://{host}:{port}/llama", data=json.dumps(seq).encode(),
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=120) as resp:
            resp.read()
        http_lats.append(time.perf_counter() - t0)
    # Proxy-side histogram (serve/metrics.py serve_http_request_s) is
    # the source of record; the client-side list is kept only as the
    # cross-check in the note (client ms include connection setup).
    h_p50 = http_hist_pctl_ms("llama", 0.5)
    h_p99 = http_hist_pctl_ms("llama", 0.99, timeout_s=1.0)
    if h_p50 is not None:
        rows.append({
            "metric": "serve_http_latency_p50",
            "value": round(h_p50, 1), "unit": "ms",
            "note": (f"p99={h_p99:.1f}ms from the proxy's "
                     f"serve_http_request_s histogram (bucket-"
                     f"interpolated pctl; same source as /metrics); "
                     f"client-side cross-check p50="
                     f"{pctl(http_lats, 0.5) * 1000:.1f}ms via per-node "
                     f"ProxyActor (single-threaded client)"),
        })
    else:
        rows.append({
            "metric": "serve_http_latency_p50",
            "value": round(pctl(http_lats, 0.5) * 1000, 1), "unit": "ms",
            "note": f"p99={pctl(http_lats, 0.99) * 1000:.1f}ms via "
                    f"per-node ProxyActor (single-threaded client; "
                    f"proxy histogram never flushed — fallback)",
        })
    serve.delete("llama")
    return rows


def bench_autoscale(args, serve) -> list:
    rows = []
    # ---- 4: autoscale-up-under-load (CPU replicas; one chip = one TPU
    # replica, so the scaling mechanism is shown on the CPU pool)
    @serve.deployment(autoscaling_config=serve.AutoscalingConfig(
        min_replicas=1, max_replicas=4, target_ongoing_requests=2,
        upscale_delay_s=0.2, downscale_delay_s=60.0))
    class Slow:
        def __call__(self, x):
            time.sleep(0.25)
            return x

    s_handle = serve.run(Slow.bind(), name="scaler")
    s_handle.remote(0).result(timeout=60)
    t0 = time.monotonic()
    stop = t0 + (15.0 if args.quick else 30.0)
    scale_times = {}

    def pound():
        while time.monotonic() < stop:
            try:
                s_handle.remote(1).result(timeout=60)
            except Exception:
                pass

    threads = [threading.Thread(target=pound) for _ in range(12)]
    for t in threads:
        t.start()
    while time.monotonic() < stop:
        n_rep = serve.status()["scaler"]["replicas"]
        if n_rep not in scale_times:
            scale_times[n_rep] = time.monotonic() - t0
        if n_rep >= 4:
            break
        time.sleep(0.1)
    for t in threads:
        t.join()
    peak = max(scale_times)
    rows.append({
        "metric": "serve_autoscale_up",
        "value": (round(scale_times[2], 1) if 2 in scale_times else None),
        "unit": "s",
        "note": f"time to 2nd replica under 12-client load; reached "
                f"{peak} replicas ({ {k: round(v, 1) for k, v in sorted(scale_times.items())} }); "
                f"CPU replicas — single chip hosts one TPU replica",
    })
    return rows


def _write(rows, args) -> None:
    if args.cpu:
        for r in rows:
            if "cpu backend" not in r["note"]:  # disagg rows self-tag
                r["note"] += (f"; {args.model} model, cpu backend "
                              f"(nearest-rank pctl)")
    out = {
        "artifact": "BENCH_SERVE",
        "model": f"llama-{args.model} prefill, seq 128, bf32 defaults",
        "data_plane": "per-node ProxyActor (serve/proxy.py)",
        "device_probe": {
            "note": "raw jitted step on this chip (no serving stack): "
                    "bucket 8 = 61 ms, bucket 32 = 106 ms, bucket 64 = "
                    "109 ms/batch (588 seq/s, 75k tok/s). The closed-loop "
                    "gap vs serve_throughput is client+router CPU on the "
                    "1-core host, not the data plane.",
            "bucket64_seq_per_s": 588,
        },
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_SERVE.json")
    # Merge-preserve: replace exactly the rows this run re-measured —
    # clobbering bench_decode.py's decode/paged rows (as the pre-fix
    # version did) silently erased half the artifact.
    if os.path.exists(path):
        with open(path) as f:
            old = json.load(f)
        emitted = {r["metric"] for r in rows}
        out["rows"] = [r for r in old.get("rows", [])
                       if r["metric"] not in emitted] + rows
        for key, val in old.items():
            out.setdefault(key, val)
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
