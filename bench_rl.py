"""RL throughput benchmark: env-steps/sec for PPO, DQN, SAC + multi-agent.

Writes BENCH_RL.json — the committed artifact for BASELINE.json's
"PPO env-steps/sec tracked" north star (VERDICT r2 #6: the number must
live in the repo, not die in a result dict). Box-bound absolute numbers;
the shape (sample + learn overlap, steps/sec accounting identical to the
reference's ``env_runner_sampling_speed`` release test) is the comparison.

Usage: python bench_rl.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import os
import time

# RL inference/learning runs on host CPU by design (env runners are CPU
# actors; the tunneled TPU chip adds ~ms of round-trip per tiny policy op).
os.environ["JAX_PLATFORMS"] = "cpu"

# CartPole-scale MLP learners and samplers are CPU-bound by design (a
# tunneled chip adds a fixed dispatch floor per tiny jitted call);
# pinning the platform — overriding the machine-wide JAX_PLATFORMS=axon
# — also keeps the bench runnable when the accelerator transport is
# down. Workers inherit the env at fork; THIS process needs the config
# update too because a site hook imports jax before this line runs.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import ray_tpu  # noqa: E402


def bench(name: str, algo, iters: int, warmup: int = 2,
          note: str = "") -> dict:
    for _ in range(warmup):  # compile + worker fork
        algo.train()
    t0 = time.monotonic()
    steps = 0
    returns = None
    for _ in range(iters):
        m = algo.train()
        steps += m["env_steps_this_iter"] if "env_steps_this_iter" in m \
            else m["env_steps_total"]
        returns = m.get("episode_return_mean", returns)
    wall = time.monotonic() - t0
    algo.stop()
    row = {"algo": name, "env_steps_per_sec": round(steps / wall, 1),
           "iters": iters, "wall_s": round(wall, 1),
           "episode_return_mean": returns}
    if note:
        row["note"] = note
    print(json.dumps(row))
    return row


def bench_to_reward(name, algo, target, max_iters, note=""):
    """Run-to-reward row (VERDICT r4 Weak #5: the artifact must showcase
    LEARNING configurations, not just throughput shapes): train until the
    return target or the iteration budget, record best + wall."""
    t0 = time.monotonic()
    best = None
    steps = 0
    iters = 0
    for _ in range(max_iters):
        m = algo.train()
        iters += 1
        steps = m.get("env_steps_total", steps)
        r = m.get("episode_return_mean")
        if r is not None:
            best = r if best is None else max(best, r)
        if best is not None and best >= target:
            break
    algo.stop()
    wall = time.monotonic() - t0
    row = {"algo": name, "mode": "run-to-reward",
           "best_return": round(best, 1) if best is not None else None,
           "target": target, "reached_target": bool(
               best is not None and best >= target),
           "iters": iters, "env_steps_total": steps,
           "wall_s": round(wall, 1)}
    if note:
        row["note"] = note
    print(json.dumps(row))
    return row


def bench_distributed(iters: int) -> list:
    """Podracer substrate scaling rows (ISSUE 10): env-steps/s and
    learner updates/s over 1 -> 4 rollout actors, plus the parameter-
    staleness distribution each fleet size produces (read from the
    plane's metrics histograms, not ad-hoc lists)."""
    from ray_tpu.rl import DQNConfig

    rows = []
    for actors in (1, 2, 4):
        algo = DQNConfig(env="CartPole-v1", seed=0).training(
            rollout_length=32, learning_starts=256, batch_size=128,
            train_batches_per_iter=8).distributed_rollouts(
            actors, num_envs_per_actor=4).build()
        try:
            for _ in range(2):  # compile + fleet spin-up
                algo.train()
            t0 = time.monotonic()
            steps = 0
            updates0 = algo._learner_steps
            m = {}
            for _ in range(iters):
                m = algo.train()
                steps += m["env_steps_this_iter"]
            wall = time.monotonic() - t0
            stale = (m.get("rl") or {}).get("staleness") or {}
            row = {
                "algo": "DistributedDQN/CartPole-v1",
                "section": "distributed",
                "rollout_actors": actors,
                "env_steps_per_sec": round(steps / wall, 1),
                "learner_updates_per_sec": round(
                    (algo._learner_steps - updates0) / wall, 1),
                "staleness_p50": stale.get("p50"),
                "staleness_p99": stale.get("p99"),
                "iters": iters, "wall_s": round(wall, 1),
                "note": "object-plane shards + pubsub weight fan-out; "
                        "1-box CPU host (actors time-slice one core — "
                        "the scaling story needs a multi-core rig)",
            }
        finally:
            algo.stop()
        print(json.dumps(row))
        rows.append(row)
    return rows


def classic_rows(iters: int) -> list:
    from ray_tpu.rl import (APPOConfig, DQNConfig, MultiAgentPPOConfig,
                            PPOConfig, SACConfig)

    rows = [
        bench("PPO/CartPole-v1", PPOConfig(
            env="CartPole-v1", num_env_runners=2, seed=0).build(),
            iters),
        bench("APPO/CartPole-v1", APPOConfig(
            env="CartPole-v1", num_env_runners=2, seed=0).build(),
            iters,
            note="async clipped surrogate over the IMPALA pipeline; "
                 "samplers never wait for the learner"),
        # Replay ratio rebalanced for a THROUGHPUT row (VERDICT r3 Weak
        # #5): the learning default (32 jitted replay updates/iter)
        # spends ~16 train samples per env step — right for sample
        # efficiency, nonsensical as a steps/sec headline on a 1-core
        # box. 4 updates/iter ~= 2 train samples per env step, the
        # classic DQN ratio.
        bench("DQN/CartPole-v1", DQNConfig(
            env="CartPole-v1", num_env_runners=2, seed=0).training(
            train_batches_per_iter=4).build(),
            iters,
            note="replay ratio ~2 train samples/env step (throughput "
                 "config; learning default is 32 updates/iter)"),
        bench("SAC/Pendulum-v1", SACConfig(
            env="Pendulum-v1", num_env_runners=2, seed=0).build(),
            iters,
            note="64 jitted updates/iter (learning config kept: SAC is "
                 "update-dominated by design)"),
        bench("MultiAgentPPO/GuideFollow", MultiAgentPPOConfig(
            num_env_runners=2, episodes_per_sample=16, seed=0).build(),
            iters),
        # Learning-configuration rows: same algorithms at their LEARNING
        # defaults, run to a reward target (what the throughput rows
        # above deliberately trade away).
        bench_to_reward(
            "DQN/CartPole-v1", DQNConfig(
                env="CartPole-v1", num_env_runners=2, seed=1).training(
                rollout_length=32, learning_starts=500).build(),
            target=120.0, max_iters=120,
            note="learning default: 32 replay updates/iter"),
        bench_to_reward(
            "SAC/Pendulum-v1", SACConfig(
                env="Pendulum-v1", num_env_runners=2, seed=1).build(),
            target=-900.0, max_iters=60,
            note="auto-alpha squashed-Gaussian; Pendulum random ~ -1200,"
                 " solved ~ -150"),
    ]
    for row in rows:
        row["section"] = "classic"
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=15)
    ap.add_argument(
        "--sections", default="classic,distributed",
        help="comma-set of row groups to (re)measure: classic, "
             "distributed. Only the selected groups' rows are replaced "
             "in BENCH_RL.json; the rest are preserved (PR 6 idiom).")
    args = ap.parse_args()
    sections = {s.strip() for s in args.sections.split(",") if s.strip()}

    ray_tpu.init(num_cpus=6)
    rows = []
    try:
        if "classic" in sections:
            rows += classic_rows(args.iters)
        if "distributed" in sections:
            rows += bench_distributed(args.iters)
    finally:
        ray_tpu.shutdown()

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_RL.json")
    out = {"metric": "rl_env_steps_per_sec",
           "host": f"{os.cpu_count()}-core", "rows": []}
    if os.path.exists(path):
        with open(path) as f:
            out = json.load(f)
        # Replace exactly the sections this run re-measured; rows
        # predating the section tag are classic rows.
        out["rows"] = [r for r in out.get("rows", [])
                       if r.get("section", "classic") not in sections]
    out["host"] = f"{os.cpu_count()}-core"
    out["rows"] = out.get("rows", []) + rows
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
