"""MFU sweep with honest timing: K steps inside one jitted+donated scan,
bracketed by a host fetch (block_until_ready under-reports on tunneled
backends; a scalar fetch forces real completion)."""

import dataclasses
import functools
import time

import jax
import optax

from ray_tpu.models import llama
from ray_tpu.parallel import train_step as ts
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.parallel.sharding import axis_rules
from ray_tpu.tpu import peak_flops_per_chip

mesh = MeshSpec(fsdp=-1).build()
PEAK = peak_flops_per_chip(getattr(jax.devices()[0], "device_kind", ""))
K = 8


def run(cfg, batch, seq=2048, accum=1):
    import jax.numpy as jnp

    opt = optax.adamw(3e-4, weight_decay=0.1)
    params = ts.init_sharded_params(lambda k: llama.init_params(cfg, k),
                                    llama.param_axes(cfg), mesh,
                                    jax.random.key(0))
    opt_state = ts.init_optimizer_state(opt, params)

    def body(carry, tokens):
        p, o = carry
        with axis_rules(mesh):
            if accum == 1:
                loss, grads = jax.value_and_grad(
                    lambda pp: llama.loss_fn(pp, {"tokens": tokens}, cfg))(p)
            else:
                # Hoist the fp32->bf16 cast out of the microbatch loop and
                # accumulate fp32 grads (gradient accumulation).
                pbf = jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16)
                    if x.dtype == jnp.float32 else x, p)
                def micro(g_acc, mtoks):
                    loss, g = jax.value_and_grad(
                        lambda pp: llama.loss_fn(
                            pp, {"tokens": mtoks}, cfg))(pbf)
                    g_acc = jax.tree.map(
                        lambda a, b: a + b.astype(a.dtype), g_acc, g)
                    return g_acc, loss
                g0 = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32),
                                  p)
                mb = tokens.reshape(accum, tokens.shape[0] // accum,
                                    tokens.shape[1])
                grads, losses = jax.lax.scan(micro, g0, mb)
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = losses.mean()
            updates, o2 = opt.update(grads, o, p)
            p2 = optax.apply_updates(p, updates)
        return (p2, o2), loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def multi(params, opt_state, toks):
        (p, o), losses = jax.lax.scan(body, (params, opt_state), toks)
        return p, o, losses

    # (K, batch, seq): shard the BATCH axis (axis 1) on the data/fsdp mesh
    # axes; the scan-step axis K stays replicated.
    from jax.sharding import NamedSharding, PartitionSpec as P

    toks = jax.device_put(
        jax.random.randint(jax.random.key(1), (K, batch, seq + 1), 0,
                           cfg.vocab_size),
        NamedSharding(mesh, P(None, ("data", "fsdp"), None)))
    params, opt_state, losses = multi(params, opt_state, toks)
    _ = float(losses[-1])
    dt = None
    for _rep in range(3):
        t0 = time.perf_counter()
        params, opt_state, losses = multi(params, opt_state, toks)
        _ = float(losses[-1])
        rep = (time.perf_counter() - t0) / K
        dt = rep if dt is None else min(dt, rep)
    tps = batch * seq / dt
    mfu = 100 * tps * llama.flops_per_token(cfg, seq) / PEAK
    return round(mfu, 2), round(tps), round(dt * 1000, 1)



import dataclasses

d1152 = llama.LlamaConfig(vocab_size=32000, dim=1152, n_layers=24, n_heads=9,
                          n_kv_heads=9, mlp_dim=4608, max_seq_len=2048)
d1280 = llama.LlamaConfig(vocab_size=32000, dim=1280, n_layers=24, n_heads=10,
                          n_kv_heads=10, mlp_dim=5120, max_seq_len=2048)
fl = lambda c, **kw: dataclasses.replace(c, attention_impl="flash", **kw)
CONFIGS = [
    ("d1280 b3x16 accum16", fl(d1280, loss_chunk=1024, fused_qkv=True,
        fused_mlp=True, embed_via_matmul=True, embed_chunk=1024), 48, 2048, 16),
    ("d1280 b4x8 accum8", fl(d1280, loss_chunk=1024, fused_qkv=True,
        fused_mlp=True, embed_via_matmul=True, embed_chunk=1024), 32, 2048, 8),
    ("d1536 b3x8 accum8",
     fl(llama.LlamaConfig(vocab_size=32000, dim=1536, n_layers=24,
                          n_heads=12, n_kv_heads=12, mlp_dim=6144,
                          max_seq_len=2048),
        loss_chunk=1024, fused_qkv=True, fused_mlp=True,
        embed_via_matmul=True, embed_chunk=1024), 24, 2048, 8),
    ("d1536 b2x8 accum8",
     fl(llama.LlamaConfig(vocab_size=32000, dim=1536, n_layers=24,
                          n_heads=12, n_kv_heads=12, mlp_dim=6144,
                          max_seq_len=2048),
        loss_chunk=1024, fused_qkv=True, fused_mlp=True,
        embed_via_matmul=True, embed_chunk=1024), 16, 2048, 8),
]

if __name__ == "__main__":
    for desc, cfg, b, seq, acc in CONFIGS:
        for attempt in range(2):
            try:
                print(desc, run(cfg, b, seq, acc),
                      f"params={cfg.num_params()/1e6:.0f}M", flush=True)
                break
            except Exception as e:  # noqa: BLE001
                msg = str(e)[:90].replace("\n", " ")
                if "remote_compile" in msg and attempt == 0:
                    print(desc, "retrying after compile-helper 500", flush=True)
                    continue
                print(desc, "FAIL", msg, flush=True)
                break
