"""MFU sweep with honest timing: K steps inside one jitted+donated scan,
bracketed by a host fetch (block_until_ready under-reports on tunneled
backends; a scalar fetch forces real completion)."""

import dataclasses
import functools
import time

import jax
import optax

from ray_tpu.models import llama
from ray_tpu.parallel import train_step as ts
from ray_tpu.parallel.mesh import MeshSpec
from ray_tpu.parallel.sharding import axis_rules
from ray_tpu.tpu import peak_flops_per_chip

mesh = MeshSpec(fsdp=-1).build()
PEAK = peak_flops_per_chip(getattr(jax.devices()[0], "device_kind", ""))
K = 8


def run(cfg, batch, seq=2048):
    opt = optax.adamw(3e-4, weight_decay=0.1)
    params = ts.init_sharded_params(lambda k: llama.init_params(cfg, k),
                                    llama.param_axes(), mesh,
                                    jax.random.key(0))
    opt_state = ts.init_optimizer_state(opt, params)

    def body(carry, tokens):
        p, o = carry
        with axis_rules(mesh):
            loss, grads = jax.value_and_grad(
                lambda pp: llama.loss_fn(pp, {"tokens": tokens}, cfg))(p)
            updates, o2 = opt.update(grads, o, p)
            p2 = optax.apply_updates(p, updates)
        return (p2, o2), loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def multi(params, opt_state, toks):
        (p, o), losses = jax.lax.scan(body, (params, opt_state), toks)
        return p, o, losses

    toks = ts.shard_batch(
        {"t": jax.random.randint(jax.random.key(1), (K, batch, seq + 1), 0,
                                 cfg.vocab_size)}, mesh)["t"]
    params, opt_state, losses = multi(params, opt_state, toks)
    _ = float(losses[-1])
    t0 = time.perf_counter()
    params, opt_state, losses = multi(params, opt_state, toks)
    _ = float(losses[-1])
    dt = (time.perf_counter() - t0) / K
    tps = batch * seq / dt
    mfu = 100 * tps * llama.flops_per_token(cfg, seq) / PEAK
    return round(mfu, 2), round(tps), round(dt * 1000, 1)



import sys

from _sweep2_configs import CONFIGS

if __name__ == "__main__":
    for desc, cfg, b, seq in CONFIGS:
        try:
            print(desc, run(cfg, b, seq),
                  f"params={cfg.num_params()/1e6:.0f}M", flush=True)
        except Exception as e:  # noqa: BLE001
            print(desc, "FAIL", str(e)[:100].replace("\n", " "), flush=True)
