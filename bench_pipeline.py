"""Pipeline-parallel training plane bench (ISSUE 14, ROADMAP #5).

Rows (merge-preserving into BENCH_TUNE.json — the existing PBT artifact
keeps its keys, pipeline rows live under ``"rows"``):

* ``pipe_act_mb_per_s_{n}s``   — inter-stage tensor bytes/s (activations
  forward + input-gradients backward) through the object plane / RPC
  write path at 2 and 4 stages;
* ``pipe_step_s_{n}s``         — wall time of one 8-microbatch optimizer
  step at that stage count;
* ``pipe_bubble_frac_m{m}_4s`` — measured bubble fraction (1 − mean
  stage occupancy / wall) at 4 stages for 2/4/8 microbatches: more
  microbatches amortize the fill/drain ramps, the 1F1B story;
* ``zero1_state_ratio_d{n}``   — ZeRO-1 per-replica optimizer-state
  bytes vs the unsharded optimizer at data = 2/4/8 (acceptance bound:
  ≤ 0.6 at data=2).

Run: ``make bench-pipeline`` (CPU host, virtual multi-host slice; the
numbers under measurement are schedule/control-plane shape, not model
FLOPs — a 1-core box time-slices the stage "hosts").
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="write /tmp instead of BENCH_TUNE.json")
    args = parser.parse_args()

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["RAY_TPU_VIRTUAL_SLICE"] = "4x4/4"

    import jax
    import numpy as np
    import optax

    import ray_tpu
    from ray_tpu.models import llama
    from ray_tpu.train.pipeline_plane import PipelinePlane, microbatches

    cfg = llama.LlamaConfig(vocab_size=128, dim=64, n_layers=4,
                            n_heads=4, n_kv_heads=2, mlp_dim=128,
                            max_seq_len=128)
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)

    def step_data(n_micro, batch=8, seq=65):
        return microbatches(
            {"tokens": rng.integers(0, cfg.vocab_size,
                                    (batch, seq)).astype(np.int32)},
            n_micro)

    rows = []
    ray_tpu.init(num_cpus=8)
    try:
        # ---------------- activation throughput at 2 / 4 stages
        for n_stages in (2, 4):
            plane = PipelinePlane(
                cfg, params, n_stages=n_stages, n_microbatches=8,
                lr=1e-3, window=n_stages,
                name=f"bench-{n_stages}s").start()
            try:
                plane.train_step(step_data(8))  # warm the stage jits
                moved0 = plane.stats()["tensor_bytes_moved"]
                t0 = time.monotonic()
                n_steps = 3
                for _ in range(n_steps):
                    plane.train_step(step_data(8))
                wall = time.monotonic() - t0
                moved = plane.stats()["tensor_bytes_moved"] - moved0
                rows.append({
                    "metric": f"pipe_act_mb_per_s_{n_stages}s",
                    "value": round(moved / wall / 1e6, 2),
                    "unit": "MB/s",
                    "note": (f"inter-stage activation+gradient bytes "
                             f"through the object plane, {n_stages} "
                             f"stages x 8 microbatches, debug llama "
                             f"(dim {cfg.dim}, seq 64), cpu host — "
                             f"{moved} B over {n_steps} steps")})
                rows.append({
                    "metric": f"pipe_step_s_{n_stages}s",
                    "value": round(wall / n_steps, 3), "unit": "s",
                    "note": (f"one 8-microbatch 1F1B optimizer step at "
                             f"{n_stages} stages (window {n_stages}), "
                             f"warm jits, cpu host")})
            finally:
                plane.stop()

        # ---------------- bubble fraction vs microbatch count (4 stages)
        plane = PipelinePlane(cfg, params, n_stages=4, n_microbatches=2,
                              lr=1e-3, window=4,
                              name="bench-bubble").start()
        try:
            # Per-microbatch batch stays 2 rows at every m (batch=2m),
            # so every step reuses ONE warmed jit shape per stage.
            plane.n_microbatches = 8
            plane.train_step(step_data(8, batch=16))  # warm the jits
            for m in (2, 4, 8):
                plane.n_microbatches = m
                busy0 = plane.stats()["stage_busy_s"]
                t0 = time.monotonic()
                plane.train_step(step_data(m, batch=2 * m))
                wall = time.monotonic() - t0
                busy = [b - a for a, b in
                        zip(busy0, plane.stats()["stage_busy_s"])]
                bubble = 1.0 - sum(busy) / (len(busy) * wall)
                rows.append({
                    "metric": f"pipe_bubble_frac_m{m}_4s",
                    "value": round(bubble, 3), "unit": "frac",
                    "note": (f"1 - mean stage occupancy / step wall at "
                             f"4 stages, {m} microbatches (1F1B fill/"
                             f"drain ramp; the 1-core host time-slices "
                             f"stages, so the floor is scheduling "
                             f"overhead, not compute overlap)")})
        finally:
            plane.stop()
    finally:
        ray_tpu.shutdown()

    # ---------------- ZeRO-1 per-replica optimizer-state bytes
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import train_step as ts
    from ray_tpu.parallel.mesh import MeshSpec

    opt = optax.adam(1e-3)
    zcfg = llama.PRESETS["debug"]
    zparams = llama.init_params(zcfg, jax.random.key(1))
    for n_data in (2, 4, 8):
        mesh = MeshSpec(data=n_data, fsdp=1).build(
            jax.devices()[:n_data])
        rep = NamedSharding(mesh, P())
        placed = jax.device_put(
            jax.tree.map(lambda x: np.array(x), zparams),
            jax.tree.map(lambda _: rep, zparams))
        plain = ts.per_replica_state_bytes(
            ts.init_optimizer_state(opt, placed))
        z1 = ts.per_replica_state_bytes(
            ts.init_zero1_opt_state(opt, placed, mesh))
        rows.append({
            "metric": f"zero1_state_ratio_d{n_data}",
            "value": round(z1 / plain, 4), "unit": "x",
            "note": (f"ZeRO-1 per-replica optimizer-state bytes vs "
                     f"unsharded adam at data={n_data} (debug llama; "
                     f"~1/N — indivisible tiny leaves replicate). "
                     f"Acceptance: <= 0.6 at data=2")})

    out_path = "BENCH_TUNE.json"
    doc = {}
    if os.path.exists(out_path) and not args.quick:
        with open(out_path) as f:
            doc = json.load(f)
    emitted = {r["metric"] for r in rows}
    doc["rows"] = [r for r in doc.get("rows", [])
                   if r["metric"] not in emitted] + rows
    if args.quick:
        out_path = "/tmp/bench_pipeline_quick.json"
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(rows, indent=2))


if __name__ == "__main__":
    main()
