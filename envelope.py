"""Scale-envelope benchmark: measure this framework's core scalability rows
against the reference's published envelope (BASELINE.md "Core scalability
envelope"; reference harness: ``release/benchmarks/README.md:5-32`` +
``release/benchmarks/distributed/test_many_*``).

The reference measured on a 64x64-core AWS cluster; this harness runs the
multi-raylet-in-one-machine fixture (SURVEY §4) on whatever box it is given,
so absolute numbers are box-bound — the rows prove the *mechanisms* hold at
the envelope's shape (many nodes, task/actor/PG storms, broadcast fan-out)
with no deadlock and bounded latency, and record honest measured values.

Usage:  python envelope.py [--quick]          (writes ENVELOPE.md)
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time
from typing import Optional

os.environ.setdefault("RAY_TPU_object_store_memory_bytes",
                      str(512 * 1024 * 1024))

RESULTS: list[dict] = []


def row(metric: str, value, unit: str, baseline: str, note: str = "") -> None:
    RESULTS.append({"metric": metric, "value": value, "unit": unit,
                    "baseline": baseline, "note": note})
    print(f"  {metric}: {value} {unit}  (ref: {baseline})"
          + (f" — {note}" if note else ""))


def pctl(xs, p):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * p))]


# ------------------------------------------------------------------ sections


def control_plane(n_nodes: int) -> None:
    """Controller-only scale: node registry size + heartbeat absorption +
    pick_node latency under the storm (reference rows: 2,000+ nodes;
    ray_syncer/gcs resource reporting)."""
    from ray_tpu.core.controller import Controller
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.rpc import RpcClient

    print(f"[control plane @ {n_nodes} simulated nodes]")
    ctrl = Controller()
    try:
        ids = [NodeID.from_random() for _ in range(n_nodes)]
        cli = RpcClient(ctrl.address)
        t0 = time.time()
        for nid in ids:
            cli.call("register_node", nid.binary(), ("127.0.0.1", 1),
                     {"CPU": 16.0}, {})
        reg_rate = n_nodes / (time.time() - t0)

        stop = threading.Event()
        counts = [0] * 8

        def hb(i):
            c = RpcClient(ctrl.address)
            while not stop.is_set():
                for nid in ids[i::8]:
                    if stop.is_set():
                        break
                    c.call("heartbeat", nid.binary(), {"CPU": 12.0}, 3)
                    counts[i] += 1

        threads = [threading.Thread(target=hb, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        lat = []
        pc = RpcClient(ctrl.address)
        t1 = time.time()
        for _ in range(500):
            s = time.perf_counter()
            assert pc.call("pick_node", {"CPU": 1.0}, None, None, None)
            lat.append((time.perf_counter() - s) * 1000)
        elapsed = time.time() - t1
        stop.set()
        for t in threads:
            t.join(2)
        hb_rate = sum(counts) / elapsed
        row("nodes registered (control plane)", n_nodes, "nodes",
            "2,000+ nodes", f"registered at {reg_rate:,.0f}/s")
        row("heartbeat absorption", round(hb_rate), "heartbeats/s",
            f"{n_nodes} nodes @ 1 Hz needs {n_nodes}/s",
            f"{hb_rate / max(n_nodes, 1):.0f}x the 1 Hz requirement")
        row("pick_node p50 under heartbeat storm",
            round(pctl(lat, 0.5), 2), "ms", "scheduler stays responsive",
            f"p99={pctl(lat, 0.99):.2f}ms @ {n_nodes} nodes")
    finally:
        ctrl.stop()


def _rss_bytes() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")


def owner_queue_depth(n_queued: int) -> None:
    """The reference's many_tasks row (release/benchmarks/README.md:31 —
    1M+ queued on one node) is an OWNER-side queue-depth exercise: can one
    driver hold n_queued in-flight tasks (specs, return refs, lineage) and
    drain them? Runs on a single-node cluster; the 50-raylet storm row
    measures cluster scheduling separately. Reports owner-side bytes/task
    (the data-structure cost the row exists to expose) and the drain rate
    (lease-pipelined: runners hold worker leases and push ready same-shape
    tasks back-to-back, batched 16 per RPC)."""
    import gc

    import ray_tpu

    print(f"[owner queue depth @ {n_queued:,} tasks]")
    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def noop(i):
        return i

    try:
        ray_tpu.get([noop.remote(i) for i in range(200)])
        gc.collect()
        rss0 = _rss_bytes()
        t0 = time.time()
        refs = [noop.remote(i) for i in range(n_queued)]
        submit_wall = time.time() - t0
        rss_mid = _rss_bytes()
        out = ray_tpu.get(refs, timeout=3600)
        drain_wall = time.time() - t0
        assert len(out) == n_queued and out[12345] == 12345
        per_task = max(0, rss_mid - rss0) / n_queued
        row("tasks queued in one owner", n_queued, "tasks",
            "1,000,000+ queued on one node",
            f"submitted in {submit_wall:.0f}s "
            f"({n_queued / submit_wall:,.0f}/s), drained in "
            f"{drain_wall:.0f}s ({n_queued / drain_wall:,.0f}/s), "
            f"~{per_task:,.0f} B/task owner-side")
    finally:
        ray_tpu.shutdown()


def actor_surge(n_actors: int, wave: int = 500,
                raise_pid_max: Optional[bool] = None) -> None:
    """Dedicated single-node actor surge (the 50-raylet fixture shares one
    core across every subsystem; this row isolates the worker-pool path:
    forkserver warm forks + dedicated actor processes). Created in waves
    (bounding control-RPC queue depth the way any loader at this scale
    does); the row's claim is N actors LIVE simultaneously, all callable
    in one fan-out. Needs kernel.pid_max above the stock 32,768 — every
    worker is a process with ~5 threads; raising it is a SYSTEM-WIDE
    host reconfiguration, so it only happens when explicitly requested
    (``--raise-pid-max`` / ENVELOPE_RAISE_PID_MAX=1) and is logged."""
    import ray_tpu

    if raise_pid_max is None:
        raise_pid_max = os.environ.get("ENVELOPE_RAISE_PID_MAX") == "1"
    if raise_pid_max:
        try:  # 3,000+ workers x ~5 threads outgrow the stock pid space
            with open("/proc/sys/kernel/pid_max", "r+") as f:
                old = int(f.read())
                if old < 4_194_304:
                    f.seek(0)
                    f.write("4194304")
                    print(f"[actor_surge] raised kernel.pid_max "
                          f"{old} -> 4194304 (system-wide; persists after "
                          f"this benchmark)", flush=True)
        except OSError:
            pass

    print(f"[actor surge @ {n_actors:,} actors]")
    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    class Member:
        def pid(self):
            return os.getpid()

    try:
        t0 = time.time()
        actors = []
        while len(actors) < n_actors:
            batch = [Member.options(num_cpus=0).remote()
                     for _ in range(min(wave, n_actors - len(actors)))]
            ray_tpu.get([a.pid.remote() for a in batch], timeout=900)
            actors += batch
        mid = time.time()
        pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=1800)
        wall = time.time() - t0
        assert len(set(pids)) == n_actors
        row("actors on one node (surge)", n_actors, "actors",
            "40,000+ (4,096 cores)",
            f"all LIVE simultaneously; built in {wall:.1f}s "
            f"({n_actors / wall:.1f} actors/s, forkserver warm forks, "
            f"1 core), one {n_actors}-wide fan-out call in "
            f"{time.time() - mid:.1f}s")
        t0 = time.time()
        for a in actors:
            ray_tpu.kill(a)
        print(f"  killed in {time.time() - t0:.1f}s")
    finally:
        ray_tpu.shutdown()


def real_cluster(n_nodes: int, n_tasks: int, n_queued: int, n_pgs: int,
                 n_actors: int, broadcast_mb: int) -> None:
    """Full-stack rows on a real multi-raylet cluster: every node is a live
    supervisor (RPC server, worker pool, shm store, heartbeats); workers are
    real subprocesses."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.placement import placement_group, remove_placement_group

    print(f"[real cluster @ {n_nodes} raylets]")
    cluster = Cluster(initialize_head=False)
    t0 = time.time()
    for _ in range(n_nodes):
        cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(60)
    row("raylets in one machine", n_nodes, "nodes", "2,000+ (64 hosts)",
        f"brought up in {time.time() - t0:.1f}s, all heartbeating")
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def noop(x):
        return x

    try:
        # Warm the worker pools so the task rows measure scheduling, not
        # process forking.
        ray_tpu.get([noop.remote(i) for i in range(2 * n_nodes)], timeout=300)

        # --- concurrent task storm over all nodes
        t_storm = time.time()
        t0 = time.time()
        refs = [noop.remote(i) for i in range(n_tasks)]
        out = ray_tpu.get(refs, timeout=600)
        wall = time.time() - t0
        assert out == list(range(n_tasks))
        row("concurrent tasks (cluster-wide storm)", n_tasks, "tasks",
            "10,000+ simultaneous",
            f"{n_tasks / wall:,.0f} tasks/s over {n_nodes} nodes")

        # Scheduling latency from the controller's task-event buffer
        # (submitted_ts -> lease_ts is exactly time-to-scheduled).
        time.sleep(2.0)  # let workers flush event buffers
        from ray_tpu.core.runtime import get_core_worker

        core = get_core_worker()
        events = core.controller.call("list_task_events", n_tasks + 2000)
        sched = [(e["lease_ts"] - e["submitted_ts"]) * 1000 for e in events
                 if e.get("lease_ts") and e.get("submitted_ts")
                 and e.get("state") == "FINISHED"
                 # Storm window only: warm-up leases include worker forks.
                 and e["submitted_ts"] >= t_storm]
        if sched:
            row("scheduling latency p50", round(pctl(sched, 0.5), 1), "ms",
                "(not published per-task)",
                f"p99={pctl(sched, 0.99):.1f}ms over {len(sched)} tasks "
                f"(0 ms = lease-pipelined: the runner already held a "
                f"compatible worker lease, so the task paid no per-task "
                f"pick+lease round trip at all)")

        # --- tasks queued in one owner (client-side queue depth)
        t0 = time.time()
        refs = [noop.remote(i) for i in range(n_queued)]
        submit_wall = time.time() - t0
        out = ray_tpu.get(refs, timeout=900)
        drain_wall = time.time() - t0
        assert len(out) == n_queued
        row("tasks queued (50-raylet fixture)", n_queued, "tasks",
            "(cluster variant of the 1M owner-depth row)",
            f"submitted in {submit_wall:.1f}s, drained in {drain_wall:.1f}s "
            f"({n_queued / drain_wall:,.0f}/s)")

        # --- placement group storm
        t0 = time.time()
        pgs = [placement_group([{"CPU": 0.01}], strategy="PACK")
               for _ in range(n_pgs)]
        assert all(pg.ready(timeout=120) for pg in pgs)
        ready_wall = time.time() - t0
        for pg in pgs:
            remove_placement_group(pg)
        row("simultaneous placement groups", n_pgs, "PGs",
            "1,000+ simultaneous",
            f"all ready in {ready_wall:.1f}s "
            f"({n_pgs / ready_wall:,.0f} PGs/s), removed clean")

        # --- actor storm (each actor = dedicated worker process)
        @ray_tpu.remote
        class Member:
            def pid(self):
                return os.getpid()

        t0 = time.time()
        actors = [Member.options(num_cpus=0.01).remote()
                  for _ in range(n_actors)]
        pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=600)
        wall = time.time() - t0
        assert len(set(pids)) == n_actors
        row("actors in cluster", n_actors, "actors", "40,000+ (4,096 cores)",
            f"all ALIVE + called in {wall:.1f}s "
            f"({n_actors / wall:.1f} actors/s via forkserver warm forks)")
        for a in actors:
            ray_tpu.kill(a)

        # --- object broadcast: one put, fetched by a task on every node
        import numpy as np

        blob = np.ones(broadcast_mb * 1024 * 1024, dtype=np.uint8)
        blob_ref = ray_tpu.put(blob)

        @ray_tpu.remote
        def fetch(arr):
            return int(arr.nbytes)

        t0 = time.time()
        sizes = ray_tpu.get(
            [fetch.options(scheduling_strategy="spread").remote(blob_ref)
             for _ in range(n_nodes)], timeout=600)
        wall = time.time() - t0
        assert all(s == blob.nbytes for s in sizes)
        gb = blob.nbytes * n_nodes / 1e9
        row("object broadcast", f"{broadcast_mb} MiB -> {n_nodes}", "nodes",
            "1 GiB -> 50+ nodes",
            f"{gb / wall:.2f} GB/s aggregate ({wall:.1f}s, chunked pulls)")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def single_node_objects(n_args: int, n_returns: int, n_get: int,
                        big_gb: float) -> None:
    """Single-node object-plane rows (reference: many_args/many_returns/
    many_objects + max get size, release/benchmarks/README.md:26-32)."""
    import numpy as np

    import ray_tpu

    print("[single-node object plane]")
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def count(*args):
            return len(args)

        refs = [ray_tpu.put(i) for i in range(n_args)]
        t0 = time.time()
        assert ray_tpu.get(count.remote(*refs), timeout=600) == n_args
        row("object args to a single task", n_args, "args", "10,000+",
            f"{time.time() - t0:.1f}s incl. arg resolution")

        @ray_tpu.remote(num_returns=n_returns)
        def fan_out():
            return tuple(range(n_returns))

        t0 = time.time()
        outs = ray_tpu.get(list(fan_out.remote()), timeout=600)
        assert len(outs) == n_returns
        row("returns from a single task", n_returns, "returns", "3,000+",
            f"{time.time() - t0:.1f}s")

        refs = [ray_tpu.put(np.frombuffer(os.urandom(128), dtype=np.uint8))
                for _ in range(n_get)]
        t0 = time.time()
        got = ray_tpu.get(refs, timeout=600)
        assert len(got) == n_get
        row("objects in a single get", n_get, "objects", "10,000+",
            f"{time.time() - t0:.1f}s")

        big = np.ones(int(big_gb * 1024 ** 3), dtype=np.uint8)
        t0 = time.time()
        back = ray_tpu.get(ray_tpu.put(big), timeout=600)
        assert back.nbytes == big.nbytes
        row("large numpy through put/get", round(big_gb, 1), "GiB",
            "100 GiB+ (244 GB box)",
            f"{big.nbytes / 1e9 / (time.time() - t0):.2f} GB/s round-trip "
            f"({big_gb:.0f}x the 2 GiB store: disk-spill-backed)")
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- reporting


def write_report(path: str, quick: bool) -> None:
    import platform

    lines = [
        "# ENVELOPE — measured scale envelope vs the reference's published "
        "rows",
        "",
        f"Produced by `python envelope.py{' --quick' if quick else ''}` on "
        f"a {os.cpu_count()}-core {platform.machine()} box "
        f"(multi-raylet-in-one-machine fixture; the reference's numbers "
        f"are from a 64-host AWS cluster, so shapes — not absolutes — are "
        f"the comparison).",
        "",
        "| Row | Measured | Reference envelope | Notes |",
        "|---|---|---|---|",
    ]
    for r in RESULTS:
        lines.append(f"| {r['metric']} | {r['value']} {r['unit']} | "
                     f"{r['baseline']} | {r['note']} |")
    lines += [
        "",
        "Notes:",
        "",
        "- **Actors**: forkserver warm forks (~10 ms each); the burst rate "
        "is 50-60 actors/s on an otherwise-idle box (see "
        "`tests/test_scale_envelope.py::test_actor_surge_forkserver`) — "
        "the row above runs inside the full 50-raylet fixture where every "
        "subsystem shares the one core.",
        "- **Broadcast**: every row here is CPU-bound, not topology-bound "
        "— all 50 'nodes' share one core, so aggregate GB/s ~= single-"
        "stream GB/s. The binomial-tree broadcast "
        "(`object_broadcast_fanout`, default off on one host for exactly "
        "this reason) spreads pulls over replica nodes on real multi-host "
        "clusters; its mechanism (slot leases, replica registration, "
        "failure pruning) is tested in "
        "`test_object_plane.py::test_broadcast_tree_forms_and_releases`.",
        "- **Node-death detection** now defaults to ~60 s of missed beats "
        "(reference parity, ray_config_def.h:842-846): the old 5 s "
        "threshold reaped LIVE nodes' actors during the 1000-actor storm.",
        "",
        "CI-runnable slice: `tests/test_scale_envelope.py` (reduced sizes, "
        "same mechanisms, asserts completion + latency bounds).",
        "",
    ]
    with open(path, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {path}")


PHASES = {
    "control": lambda q: control_plane(500 if q else 2000),
    "queue": lambda q: owner_queue_depth(20000 if q else 1_000_000),
    "surge": lambda q: actor_surge(100 if q else 3000),
    "cluster": lambda q: real_cluster(
        n_nodes=20 if q else 50, n_tasks=1000 if q else 5000,
        n_queued=2000 if q else 20000, n_pgs=50 if q else 1000,
        n_actors=20 if q else 1000, broadcast_mb=16 if q else 256),
    "objects": lambda q: single_node_objects(
        2000 if q else 10000, 500 if q else 3000,
        2000 if q else 10000, 0.25 if q else 10.0),
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="reduced sizes (CI-scale smoke)")
    ap.add_argument("--phase", choices=sorted(PHASES),
                    help="run ONE phase and dump its rows as JSON "
                         "(internal: the parent isolates phases in "
                         "subprocesses)")
    ap.add_argument("--rows-out", default=None)
    ap.add_argument("--raise-pid-max", action="store_true",
                    help="allow the surge phase to raise kernel.pid_max "
                         "system-wide (logged; off by default)")
    args = ap.parse_args()
    if args.raise_pid_max:
        # Exported so the phase SUBPROCESSES (which re-run this script
        # with --phase) see the opt-in too.
        os.environ["ENVELOPE_RAISE_PID_MAX"] = "1"
    t0 = time.time()
    if args.phase:
        PHASES[args.phase](args.quick)
        if args.rows_out:
            with open(args.rows_out, "w") as f:
                json.dump(RESULTS, f)
        return
    # Each phase runs in its own SUBPROCESS: a million dead ObjectRefs
    # (or 3,000 reaped actor handles) from one phase must not pollute the
    # next phase's timings or control RPCs — and a phase crash can't take
    # the report down with it.
    import subprocess
    import tempfile

    for name in ("control", "queue", "surge", "cluster", "objects"):
        out = tempfile.mktemp(suffix=f"_{name}.json")
        cmd = [sys.executable, os.path.abspath(__file__),
               "--phase", name, "--rows-out", out]
        if args.quick:
            cmd.append("--quick")
        res = subprocess.run(cmd)
        if res.returncode == 0 and os.path.exists(out):
            with open(out) as f:
                RESULTS.extend(json.load(f))
            os.unlink(out)
        else:
            RESULTS.append({"metric": f"phase {name}", "value": "FAILED",
                            "unit": "", "baseline": "",
                            "note": f"exit code {res.returncode}"})
    write_report(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "ENVELOPE.md"), args.quick)
    print(json.dumps({"rows": len(RESULTS),
                      "wall_s": round(time.time() - t0, 1)}))


if __name__ == "__main__":
    main()
